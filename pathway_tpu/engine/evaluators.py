"""Incremental operator evaluators — the differential-dataflow replacement.

Each parse-graph node kind gets an evaluator that consumes input ``Delta`` batches and emits an
output ``Delta`` per commit, maintaining whatever keyed state incrementality requires. This
mirrors the reference's DD operator implementations in ``src/engine/dataflow.rs`` (joins,
groupby, ix, concat, flatten, sort via prev/next) at batch granularity. Dense numeric work
inside a batch (expression trees, reducer sums, KNN search) is delegated to vectorized
numpy/JAX kernels.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from pathway_tpu.engine import expression_evaluator as ee
from pathway_tpu.engine.columnar import ERROR, Delta, Error, StateTable, empty_keys, objarray
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals.keys import (
    KEY_DTYPE,
    Pointer,
    broadcast_key,
    key_bytes,
    keys_from_values,
    keys_to_pointers,
    pointer_from,
    pointers_to_keys,
)
from pathway_tpu.internals.reducers import _IdMarker, _SeqMarker


class UnpicklableStateError(Exception):
    """Operator state can't be checkpointed; the journal must keep full history."""


class Evaluator:
    def __init__(self, node: pg.Node, runner: Any):
        self.node = node
        self.runner = runner
        self.output_columns: List[str] = (
            node.output.column_names() if node.output is not None else []
        )

    def process(self, input_deltas: List[Delta]) -> Delta:
        raise NotImplementedError

    # -- operator snapshots (reference ``operator_snapshot.rs``) -------------

    _NON_STATE_ATTRS = ("node", "runner", "output_columns")

    def state_dict(self) -> Dict[str, bytes]:
        """Picklable per-attribute snapshot of this operator's incremental state.
        Graph-config attributes (expressions, callbacks) are excluded by name via
        ``_NON_STATE_ATTRS`` — they are rebuilt identically from the (sig-checked) graph
        on restore. A *state* attribute that fails to pickle aborts the checkpoint
        (``UnpicklableStateError``): silently dropping it would compact away journal
        history the restore then cannot reconstruct."""
        import pickle

        out: Dict[str, bytes] = {}
        for name, value in self.__dict__.items():
            if name in self._NON_STATE_ATTRS:
                continue
            try:
                out[name] = pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
            except Exception as exc:
                raise UnpicklableStateError(
                    f"{type(self).__name__}.{name} is not picklable ({exc}); "
                    "operator checkpointing is unavailable for this pipeline"
                ) from exc
        return out

    def load_state_dict(self, state: Dict[str, bytes]) -> None:
        import pickle

        for name, blob in state.items():
            self.__dict__[name] = pickle.loads(blob)

    # -- helpers ------------------------------------------------------------

    def _resolver_for(self, table: Any, delta: Delta) -> Callable[[expr.ColumnReference], np.ndarray]:
        """Resolve column refs against a delta of ``table``; cross-table refs hit state.

        Retraction rows resolve cross-table refs against the *retracted* values: when the
        referenced table replaced a key this commit (a -1/+1 pair on the same key), the
        materialized state already holds the new value, but a retraction must carry what
        was originally emitted (DD value-matched semantics — ``dataflow.rs`` joins match
        on values, not on current state)."""

        def resolver(ref: expr.ColumnReference) -> np.ndarray:
            if ref.table is table:
                if ref.name == "id":
                    out = np.empty(len(delta), dtype=object)
                    out[:] = keys_to_pointers(delta.keys)
                    return out
                return delta.columns[ref.name]
            # cross-table reference: same-universe lookup by key in materialized state
            state = self.runner.state_of(ref.table._node)
            if ref.name == "id":
                out = np.empty(len(delta), dtype=object)
                out[:] = keys_to_pointers(delta.keys)
                return out
            slots = state.lookup(delta.keys)
            hit = slots >= 0
            if hit.all() and len(state):
                out = state.gather(ref.name, slots)  # fancy indexing already copied
            else:
                # a same-universe reference must hit: a miss means the tables' key sets
                # genuinely differ (e.g. select over a reindexed table referencing the
                # pre-reindex table) — poison instead of silently yielding None
                out = np.empty(len(delta), dtype=object)
                out[:] = ERROR
                if hit.any():
                    out[hit] = state.gather(ref.name, slots[hit])
            if np.any(delta.diffs < 0):
                # retraction rows resolve against the *retracted* upstream values when
                # the referenced table replaced the key this commit (see docstring)
                ref_delta = self.runner.current_delta_of(ref.table._node)
                if ref_delta is not None and len(ref_delta):
                    neg = np.nonzero(ref_delta.diffs < 0)[0]
                    ref_col = ref_delta.columns.get(ref.name)
                    if len(neg) and ref_col is not None:
                        from pathway_tpu.engine.index import KeyIndex

                        ret_idx = KeyIndex(len(neg))
                        ret_slots, _ = ret_idx.upsert(ref_delta.keys[neg])
                        slot_values = np.empty(ret_idx.slot_bound(), dtype=ref_col.dtype)
                        slot_values[ret_slots] = ref_col[neg]
                        mine = np.nonzero(delta.diffs < 0)[0]
                        found = ret_idx.lookup(delta.keys[mine])
                        use = found >= 0
                        if use.any():
                            if out.dtype != object and out.dtype != slot_values.dtype:
                                out = out.astype(object)
                            out[mine[use]] = slot_values[found[use]]
            return ee._tidy(out) if out.dtype == object else out

        return resolver

    def _eval_exprs(
        self, exprs: Dict[str, expr.ColumnExpression], table: Any, delta: Delta
    ) -> Dict[str, np.ndarray]:
        resolver = self._resolver_for(table, delta)
        return {
            name: ee.evaluate(e, len(delta), resolver, keys=delta.keys)
            for name, e in exprs.items()
        }


class InputEvaluator(Evaluator):
    """Source node: pulls batches from its DataSource each commit."""

    def process(self, input_deltas: List[Delta]) -> Delta:
        source = self.node.config["source"]
        delta = source.next_batch(self.output_columns)
        if len(delta) == 0:
            return delta
        # a keyed upsert stream (e.g. Debezium CDC) can retract and re-add the same key
        # within one commit; net the multiplicities so state application is order-free
        # (reference UpsertSession semantics, adaptors.rs:67)
        return delta.consolidated()


class RowwiseEvaluator(Evaluator):
    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return Delta.empty(self.output_columns)
        table = self.node.inputs[0]
        columns = self._eval_exprs(self.node.config["exprs"], table, delta)
        return Delta(delta.keys, delta.diffs, columns)


class FilterEvaluator(Evaluator):
    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return Delta.empty(self.output_columns)
        table = self.node.inputs[0]
        resolver = self._resolver_for(table, delta)
        mask = ee.evaluate(self.node.config["expression"], len(delta), resolver)
        if mask.dtype == object:
            mask = np.frompyfunc(lambda v: bool(v) if not isinstance(v, Error) else False, 1, 1)(
                mask
            ).astype(bool)
        return delta.select(mask.astype(bool))


class ReindexEvaluator(Evaluator):
    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return Delta.empty(self.output_columns)
        table = self.node.inputs[0]
        resolver = self._resolver_for(table, delta)
        new_ids = ee.evaluate(self.node.config["expression"], len(delta), resolver)
        keys = pointers_to_keys(
            [p if isinstance(p, Pointer) else pointer_from(p) for p in new_ids]
        )
        return Delta(keys, delta.diffs, dict(delta.columns))


class ConcatEvaluator(Evaluator):
    def process(self, input_deltas: List[Delta]) -> Delta:
        reindex = self.node.config.get("reindex", False)
        parts = []
        for i, delta in enumerate(input_deltas):
            if len(delta) == 0:
                continue
            if reindex:
                new_keys = np.empty(len(delta), dtype=KEY_DTYPE)
                for j in range(len(delta)):
                    p = pointer_from(Pointer(int(delta.keys[j]["hi"]), int(delta.keys[j]["lo"])), i)
                    new_keys[j]["hi"], new_keys[j]["lo"] = p.hi, p.lo
                delta = Delta(new_keys, delta.diffs, delta.columns)
            parts.append(delta)
        return Delta.concat(parts, self.output_columns)


def _rows_equal(a: Optional[tuple], b: Optional[tuple]) -> bool:
    if a is None or b is None:
        return a is b
    for va, vb in zip(a, b):
        if va is vb:
            continue
        if isinstance(va, np.ndarray) or isinstance(vb, np.ndarray):
            if not (
                isinstance(va, np.ndarray)
                and isinstance(vb, np.ndarray)
                and np.array_equal(va, vb)
            ):
                return False
        elif not va == vb:
            return False
    return True


class GroupbyEvaluator(Evaluator):
    """Incremental groupby-reduce (reference ``reduce.rs`` + DD reduce).

    The whole commit batch is processed columnar: group keys derive from one vectorized
    hash (``keys_from_values``, native xxh3), rows map to dense segment ids via
    ``np.unique``, semigroup reducers (count/sum/avg) update through segment kernels
    (``pathway_tpu.ops.segment``), multiset reducers batch through ``Counter.update``,
    and output expressions evaluate once over all touched groups."""

    # reducer_leaves is graph config: checkpoints must not replace it — identity (id())
    # keys the leaf-value mapping
    _NON_STATE_ATTRS = Evaluator._NON_STATE_ATTRS + ("reducer_leaves",)

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.groups: Dict[bytes, Dict[str, Any]] = {}
        # per output column that is a reducer tree: list of ReducerExpressions inside
        self.reducer_leaves: List[expr.ReducerExpression] = []
        self._collect_reducers(node.config["out_exprs"])
        self.seq = 0

    def _collect_reducers(self, out_exprs: Dict[str, expr.ColumnExpression]) -> None:
        seen: set[int] = set()

        def walk(e: expr.ColumnExpression) -> None:
            if isinstance(e, expr.ReducerExpression):
                if id(e) not in seen:
                    seen.add(id(e))
                    self.reducer_leaves.append(e)
                return
            for d in e._deps():
                walk(d)

        for e in out_exprs.values():
            walk(e)

    def _rows_for_groups(self, groups: List[Dict[str, Any]]) -> List[tuple]:
        """Output rows (tuples in ``output_columns`` order) for the given groups: the
        out-expression tree evaluated once, vectorized over all groups, with reducer
        leaves bound to accumulator values."""
        if not groups:
            return []
        leaf_value_arrays: Dict[int, np.ndarray] = {}
        for li, leaf in enumerate(self.reducer_leaves):
            leaf_value_arrays[id(leaf)] = objarray(
                [g["accs"][li].value() for g in groups]
            )
        grouping_names = self.node.config["grouping_names"]
        gval_arrays = {
            name: objarray([g["gvals"][gi] for g in groups])
            for gi, name in enumerate(grouping_names)
        }

        class _GroupEval(ee.ExpressionEvaluator):
            def _eval_ReducerExpression(self, re: expr.ReducerExpression) -> np.ndarray:
                return leaf_value_arrays[id(re)]

            def _eval_ColumnReference(self, ref: expr.ColumnReference) -> np.ndarray:
                return gval_arrays[ref.name]

        evaluator = _GroupEval(ee.EvalContext(len(groups), lambda ref: None))
        out_exprs = self.node.config["out_exprs"]
        out_cols = [list(evaluator.eval(out_exprs[name])) for name in self.output_columns]
        return list(zip(*out_cols)) if out_cols else [() for _ in groups]

    def load_state_dict(self, state: Dict[str, bytes]) -> None:
        super().load_state_dict(state)
        # checkpoints from builds predating the tuple-row cache lack "row" (or hold
        # the older dict form)
        for g in self.groups.values():
            if isinstance(g.get("row"), dict):
                g["row"] = tuple(g["row"].get(name) for name in self.output_columns)
        missing = [g for g in self.groups.values() if "row" not in g]
        for g, row in zip(missing, self._rows_for_groups(missing)):
            g["row"] = row

    def _group_keys(self, grouping_vals: List[np.ndarray], n: int, set_id: bool) -> np.ndarray:
        if not grouping_vals:
            # global reduce: every row lands in the single salt-only group
            return broadcast_key(pointer_from(), n)
        if not set_id:
            return keys_from_values(grouping_vals)
        col = grouping_vals[0]
        out = np.empty(n, dtype=KEY_DTYPE)
        for i in range(n):
            p = col[i]
            if not isinstance(p, Pointer):
                p = pointer_from(*(g[i] for g in grouping_vals))
            out[i]["hi"], out[i]["lo"] = p.hi, p.lo
        return out

    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return Delta.empty(self.output_columns)
        table = self.node.inputs[0]
        resolver = self._resolver_for(table, delta)
        n = len(delta)
        diffs = delta.diffs

        grouping_vals = [
            ee.evaluate(g, n, resolver) for g in self.node.config["grouping"]
        ]
        set_id = self.node.config.get("set_id", False)

        # reducer argument values per leaf (vectorized)
        leaf_args: List[List[np.ndarray]] = []
        for leaf in self.reducer_leaves:
            arrays = []
            for a in leaf._args:
                if isinstance(a, _IdMarker):
                    ids = np.empty(n, dtype=object)
                    ids[:] = keys_to_pointers(delta.keys)
                    arrays.append(ids)
                elif isinstance(a, _SeqMarker):
                    seqs = np.arange(self.seq, self.seq + n, dtype=np.int64)
                    arrays.append(seqs.astype(object))
                else:
                    arrays.append(ee.evaluate(a, n, resolver))
            leaf_args.append(arrays)
        self.seq += n

        # dense segment ids per row
        gkeys = self._group_keys(grouping_vals, n, set_id)
        uniq, first_idx, inverse = np.unique(
            gkeys, return_index=True, return_inverse=True
        )
        m = len(uniq)
        uniq_kb = key_bytes(uniq)

        # ensure groups exist; snapshot last-emitted rows
        touched: List[Dict[str, Any]] = []
        for j in range(m):
            group = self.groups.get(uniq_kb[j])
            if group is None:
                i0 = int(first_idx[j])
                group = {
                    "count": 0,
                    "gvals": tuple(g[i0] for g in grouping_vals),
                    "accs": [leaf._reducer.make() for leaf in self.reducer_leaves],
                    "row": None,
                }
                self.groups[uniq_kb[j]] = group
            touched.append(group)
        old_rows = [g.get("row") for g in touched]

        # apply the batch to every accumulator
        from pathway_tpu.ops.segment import segment_count, segment_slices

        cnt_delta = segment_count(inverse, m, weights=diffs)
        slices = None
        for li, (leaf, arrays) in enumerate(zip(self.reducer_leaves, leaf_args)):
            accs = [g["accs"][li] for g in touched]
            if leaf._reducer.batch_update(
                accs, arrays, diffs, inverse, m, cnt_delta, key_lo=gkeys["lo"]
            ):
                continue
            if slices is None:
                slices = segment_slices(inverse, m)
            order, starts, ends = slices
            any_retract = bool(np.any(diffs < 0))
            for j, acc in enumerate(accs):
                rows = order[starts[j] : ends[j]]
                if len(rows) == 0:
                    continue
                if not any_retract:
                    acc.insert_many(zip(*(arr[rows] for arr in arrays)))
                else:
                    # mixed commit: preserve original row order (retract/insert interleave)
                    for i in rows:
                        vals = tuple(arr[i] for arr in arrays)
                        if diffs[i] > 0:
                            acc.insert(vals)
                        else:
                            acc.retract(vals)

        alive: List[int] = []
        for j, g in enumerate(touched):
            g["count"] += int(cnt_delta[j])
            if g["count"] == 0:
                del self.groups[uniq_kb[j]]
            else:
                alive.append(j)

        # new output rows for alive groups — one vectorized expression pass
        new_rows: List[Optional[dict]] = [None] * m
        for a, row in zip(alive, self._rows_for_groups([touched[j] for j in alive])):
            new_rows[a] = row

        # emit (retract old, insert new) for changed groups
        out_key_idx: List[int] = []
        out_diffs: List[int] = []
        out_rows: List[tuple] = []
        for j in range(m):
            old, new = old_rows[j], new_rows[j]
            if _rows_equal(old, new):
                continue
            if old is not None:
                out_key_idx.append(j)
                out_diffs.append(-1)
                out_rows.append(old)
            if new is not None:
                out_key_idx.append(j)
                out_diffs.append(1)
                out_rows.append(new)
            if uniq_kb[j] in self.groups:
                self.groups[uniq_kb[j]]["row"] = new
        if not out_key_idx:
            return Delta.empty(self.output_columns)
        keys_arr = uniq[np.array(out_key_idx, dtype=np.int64)]
        cols_t = list(zip(*out_rows))
        columns = {
            name: ee._tidy(objarray(list(vals)))
            for name, vals in zip(self.output_columns, cols_t)
        }
        return Delta(keys_arr, np.array(out_diffs, dtype=np.int64), columns)


class DeduplicateEvaluator(Evaluator):
    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.current: Dict[bytes, Tuple[np.void, dict, Any]] = {}  # instance -> (key,row,value)

    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return Delta.empty(self.output_columns)
        table = self.node.inputs[0]
        resolver = self._resolver_for(table, delta)
        n = len(delta)
        value_e = self.node.config.get("value")
        instance_e = self.node.config.get("instance")
        acceptor = self.node.config.get("acceptor")
        values = ee.evaluate(value_e, n, resolver) if value_e is not None else delta.keys
        instances = (
            ee.evaluate(instance_e, n, resolver)
            if instance_e is not None
            else np.zeros(n, dtype=object)
        )
        out_keys, out_diffs, out_rows = [], [], []
        for i in range(n):
            if delta.diffs[i] < 0:
                continue  # append-only semantics (reference deduplicate is streaming-only)
            inst = instances[i]
            ib = repr(inst).encode()
            row = {c: delta.columns[c][i] for c in delta.column_names}
            val = values[i]
            cur = self.current.get(ib)
            if cur is None:
                accept = True
            else:
                accept = bool(acceptor(val, cur[2])) if acceptor is not None else True
            if not accept:
                continue
            ikey = pointer_from(inst if not isinstance(inst, np.void) else int(inst["lo"]), "dedup")
            if cur is not None:
                out_keys.append(ikey)
                out_diffs.append(-1)
                out_rows.append(cur[1])
            out_keys.append(ikey)
            out_diffs.append(1)
            out_rows.append(row)
            self.current[ib] = (delta.keys[i], row, val)
        if not out_keys:
            return Delta.empty(self.output_columns)
        columns = {
            name: ee._tidy(objarray([r[name] for r in out_rows]))
            for name in self.output_columns
        }
        return Delta(pointers_to_keys(out_keys), np.array(out_diffs, dtype=np.int64), columns)


class _JoinSide:
    """Columnar arrangement for one join side: slot-based value arrays plus a
    join-key hash index. The DD-arrangement stand-in for the join's build state —
    rows live in struct-of-arrays, so event emission gathers with fancy indexing
    instead of building per-row dicts (reference keeps these in Rust arrangements,
    ``dataflow.rs`` join over arranged collections)."""

    def __init__(self, names: Iterable[str]):
        self.names = list(names)
        self.cap = 0
        self.keys = np.empty(0, dtype=KEY_DTYPE)
        self.jk = np.empty(0, dtype=KEY_DTYPE)
        self.cols: Dict[str, np.ndarray] = {c: np.empty(0, dtype=object) for c in self.names}
        self.by_jk: Dict[bytes, Dict[bytes, int]] = {}
        self.by_kb: Dict[bytes, int] = {}
        self.free: List[int] = []

    def _grow(self, needed: int) -> None:
        new_cap = max(16, self.cap * 2, self.cap + needed)

        def grown(a: np.ndarray, dtype: Any) -> np.ndarray:
            out = np.empty(new_cap, dtype=dtype)
            out[: self.cap] = a
            return out

        self.keys = grown(self.keys, KEY_DTYPE)
        self.jk = grown(self.jk, KEY_DTYPE)
        for c in self.names:
            self.cols[c] = grown(self.cols[c], object)
        self.free.extend(range(self.cap, new_cap))
        self.cap = new_cap

    def alloc(self, k: int) -> np.ndarray:
        if k > len(self.free):
            self._grow(k - len(self.free))
        return np.array([self.free.pop() for _ in range(k)], dtype=np.int64)

    def register(self, jkb: bytes, kb: bytes, slot: int) -> None:
        old = self.by_kb.get(kb)
        if old is not None:
            # duplicate key insert: replace (mirrors dict-overwrite semantics).
            # The old row may sit in a DIFFERENT join-key bucket — find it via its
            # stored jk, not the incoming one.
            old_jkb = self.jk[old].tobytes()
            old_bucket = self.by_jk.get(old_jkb)
            if old_bucket is not None:
                old_bucket.pop(kb, None)
                if not old_bucket:
                    del self.by_jk[old_jkb]
            self.free.append(old)
        bucket = self.by_jk.get(jkb)
        if bucket is None:
            bucket = self.by_jk[jkb] = {}
        bucket[kb] = slot
        self.by_kb[kb] = slot

    def deregister(self, jkb: bytes, kb: bytes) -> int | None:
        slot = self.by_kb.pop(kb, None)
        if slot is None:
            return None
        bucket = self.by_jk.get(jkb)
        if bucket is not None:
            bucket.pop(kb, None)
            if not bucket:
                del self.by_jk[jkb]
        return slot

    def release(self, slots: Iterable[int]) -> None:
        for slot in slots:
            for c in self.names:
                self.cols[c][slot] = None
            self.free.append(slot)


class JoinEvaluator(Evaluator):
    """Symmetric incremental hash join (reference DD join replacement).

    Hot path is columnar: join keys hash in one vectorized pass, the probe loop
    tracks integer slots only, and all output expressions (plus output-key
    derivation) evaluate once over the whole event batch."""

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        from pathway_tpu.internals.joins import JoinKind

        self.kind = node.config["kind"]
        self.JoinKind = JoinKind
        self.left = _JoinSide(node.inputs[0].column_names())
        self.right = _JoinSide(node.inputs[1].column_names())

    def load_state_dict(self, state: Dict[str, bytes]) -> None:
        super().load_state_dict(state)
        # migrate checkpoints from the dict-of-dicts build (left_map/right_map)
        for attr, side_name in (("left_map", "left"), ("right_map", "right")):
            legacy = self.__dict__.pop(attr, None)
            if not legacy:
                continue
            side: _JoinSide = getattr(self, side_name)
            for jkb, rows in legacy.items():
                for kb, (ptr, row) in rows.items():
                    slot = int(side.alloc(1)[0])
                    side.keys[slot] = np.frombuffer(kb, dtype=KEY_DTYPE)[0]
                    side.jk[slot] = np.frombuffer(jkb, dtype=KEY_DTYPE)[0]
                    for c in side.names:
                        side.cols[c][slot] = row.get(c)
                    side.register(jkb, kb, slot)

    def _join_keys(self, side: str, delta: Delta) -> np.ndarray:
        table = self.node.inputs[0 if side == "left" else 1]
        exprs = self.node.config["left_on" if side == "left" else "right_on"]
        if not exprs:
            # no on-condition: every row shares the salt-only bucket (cross join)
            return broadcast_key(pointer_from(), len(delta))
        resolver = self._resolver_for(table, delta)
        arrays = [ee.evaluate(e, len(delta), resolver) for e in exprs]
        return keys_from_values(arrays)

    def process(self, input_deltas: List[Delta]) -> Delta:
        left_delta, right_delta = input_deltas
        JK = self.JoinKind
        # events as parallel lists of (diff, left_slot, right_slot); -1 = null side
        ev_d: List[int] = []
        ev_l: List[int] = []
        ev_r: List[int] = []
        freed: List[Tuple[_JoinSide, int]] = []

        def run_side(delta: Delta, side_name: str) -> None:
            if len(delta) == 0:
                return
            jkeys = self._join_keys(side_name, delta)
            is_left = side_name == "left"
            own = self.left if is_left else self.right
            other = self.right if is_left else self.left
            own_null = self.kind in ((JK.LEFT, JK.OUTER) if is_left else (JK.RIGHT, JK.OUTER))
            other_null = self.kind in ((JK.RIGHT, JK.OUTER) if is_left else (JK.LEFT, JK.OUTER))

            diffs = delta.diffs
            ins_rows = np.nonzero(diffs > 0)[0]
            # batch-store insert rows: values land in state arrays before the probe
            # loop, so events reference slots uniformly
            ins_slots = own.alloc(len(ins_rows))
            if len(ins_rows):
                own.keys[ins_slots] = delta.keys[ins_rows]
                own.jk[ins_slots] = jkeys[ins_rows]
                for c in own.names:
                    own.cols[c][ins_slots] = delta.columns[c][ins_rows]
            slot_of_row = np.full(len(delta), -1, dtype=np.int64)
            slot_of_row[ins_rows] = ins_slots

            jkb_list = key_bytes(jkeys)
            kb_list = key_bytes(delta.keys)

            def emit(d: int, own_slot: int, other_slot: int) -> None:
                ev_d.append(d)
                if is_left:
                    ev_l.append(own_slot)
                    ev_r.append(other_slot)
                else:
                    ev_l.append(other_slot)
                    ev_r.append(own_slot)

            for i in range(len(delta)):
                jkb, kb, d = jkb_list[i], kb_list[i], int(diffs[i])
                if d > 0:
                    slot = int(slot_of_row[i])
                else:
                    slot = own.by_kb.get(kb, -1)
                matches = other.by_jk.get(jkb)
                own_before = len(own.by_jk.get(jkb, ()))
                if matches:
                    for oslot in matches.values():
                        emit(d, slot, oslot)
                elif own_null:
                    emit(d, slot, -1)
                if other_null and matches:
                    if d > 0 and own_before == 0:
                        for oslot in matches.values():
                            emit(-1, -1, oslot)
                    elif d < 0 and own_before == 1:
                        for oslot in matches.values():
                            emit(1, -1, oslot)
                if d > 0:
                    own.register(jkb, kb, slot)
                else:
                    gone = own.deregister(jkb, kb)
                    if gone is not None:
                        freed.append((own, gone))  # release after emission gathers

        run_side(left_delta, "left")
        run_side(right_delta, "right")

        try:
            if not ev_d:
                return Delta.empty(self.output_columns)
            return self._emit(
                np.array(ev_d, dtype=np.int64),
                np.array(ev_l, dtype=np.int64),
                np.array(ev_r, dtype=np.int64),
            ).consolidated()
        finally:
            # slots freed only after _emit gathered their values
            for side, slot in freed:
                side.release([slot])

    def _emit(self, ev_d: np.ndarray, ev_l: np.ndarray, ev_r: np.ndarray) -> Delta:
        left_table, right_table = self.node.inputs
        exprs = self.node.config["exprs"]
        id_expr = self.node.config.get("id_expr")
        n_ev = len(ev_d)
        lmask = ev_l >= 0
        rmask = ev_r >= 0
        cache: Dict[Tuple[int, str], np.ndarray] = {}

        def gather(side: _JoinSide, slots: np.ndarray, mask: np.ndarray, name: str) -> np.ndarray:
            key = (id(side), name)
            hit = cache.get(key)
            if hit is not None:
                return hit
            out = np.empty(n_ev, dtype=object)
            out[~mask] = None
            if name == "id":
                idx = np.nonzero(mask)[0]
                ptrs = keys_to_pointers(side.keys[slots[idx]])
                for a, p in zip(idx, ptrs):
                    out[a] = p
            else:
                out[mask] = side.cols[name][slots[mask]]
            cache[key] = out
            return out

        def resolver(ref: expr.ColumnReference) -> np.ndarray:
            if ref.table is left_table:
                return ee._tidy(gather(self.left, ev_l, lmask, ref.name))
            if ref.table is right_table:
                return ee._tidy(gather(self.right, ev_r, rmask, ref.name))
            raise ValueError(f"join select references foreign table: {ref!r}")

        columns = {
            name: ee.evaluate(e, n_ev, resolver) for name, e in exprs.items()
        }

        # output keys: id_expr rows (left present) take the evaluated pointer;
        # the rest hash (left_key, right_key, "join") in one vectorized pass
        lkeys = np.zeros(n_ev, dtype=KEY_DTYPE)
        lkeys[lmask] = self.left.keys[ev_l[lmask]]
        rkeys = np.zeros(n_ev, dtype=KEY_DTYPE)
        rkeys[rmask] = self.right.keys[ev_r[rmask]]
        join_salt = np.empty(n_ev, dtype=object)
        join_salt[:] = "join"
        keys = keys_from_values([lkeys, rkeys, join_salt], masks=[lmask, rmask, None])
        if id_expr is not None and np.any(lmask):
            id_vals = ee.evaluate(id_expr, n_ev, resolver)
            for i in np.nonzero(lmask)[0]:
                p = id_vals[i]
                if isinstance(p, Pointer):
                    keys[i]["hi"], keys[i]["lo"] = p.hi, p.lo
        return Delta(keys, ev_d, columns)


class UpdateRowsEvaluator(Evaluator):
    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.base = StateTable(self.output_columns)
        self.patch = StateTable(self.output_columns)

    def process(self, input_deltas: List[Delta]) -> Delta:
        base_delta, patch_delta = input_deltas
        out_keys, out_diffs, out_rows = [], [], []

        for i in range(len(base_delta)):
            kb = base_delta.keys[i].tobytes()
            d = int(base_delta.diffs[i])
            row = {c: base_delta.columns[c][i] for c in self.output_columns}
            if self.patch.get_row(kb) is None:
                out_keys.append(base_delta.keys[i])
                out_diffs.append(d)
                out_rows.append(row)
        self.base.apply(base_delta)

        for i in range(len(patch_delta)):
            kb = patch_delta.keys[i].tobytes()
            d = int(patch_delta.diffs[i])
            row = {c: patch_delta.columns[c][i] for c in self.output_columns}
            base_row = self.base.get_row(kb)
            if d > 0:
                if base_row is not None and self.patch.get_row(kb) is None:
                    out_keys.append(patch_delta.keys[i])
                    out_diffs.append(-1)
                    out_rows.append(base_row)
                out_keys.append(patch_delta.keys[i])
                out_diffs.append(1)
                out_rows.append(row)
            else:
                out_keys.append(patch_delta.keys[i])
                out_diffs.append(-1)
                out_rows.append(row)
                if base_row is not None:
                    out_keys.append(patch_delta.keys[i])
                    out_diffs.append(1)
                    out_rows.append(base_row)
        self.patch.apply(patch_delta)

        return _delta_from_rows(
            out_keys, out_diffs, out_rows, self.output_columns
        ).consolidated()


class UpdateCellsEvaluator(Evaluator):
    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        patch_cols = [
            c for c in node.inputs[1].column_names() if c in node.inputs[0].column_names()
        ]
        self.patch_cols = patch_cols
        self.base = StateTable(self.output_columns)
        self.patch = StateTable(patch_cols)

    def _merged(self, kb: bytes, base_row: dict) -> dict:
        patch_row = self.patch.get_row(kb)
        if patch_row is None:
            return base_row
        merged = dict(base_row)
        merged.update(patch_row)
        return merged

    def process(self, input_deltas: List[Delta]) -> Delta:
        base_delta, patch_delta = input_deltas
        out_keys, out_diffs, out_rows = [], [], []

        # patch first so base rows arriving same commit see it
        self.patch.apply(
            Delta(
                patch_delta.keys,
                patch_delta.diffs,
                {c: patch_delta.columns[c] for c in self.patch_cols},
            )
        )
        for i in range(len(base_delta)):
            kb = base_delta.keys[i].tobytes()
            row = {c: base_delta.columns[c][i] for c in self.output_columns}
            out_keys.append(base_delta.keys[i])
            out_diffs.append(int(base_delta.diffs[i]))
            out_rows.append(self._merged(kb, row))
        self.base.apply(base_delta)

        # patch changes for keys NOT in this commit's base delta
        seen = {base_delta.keys[i].tobytes() for i in range(len(base_delta))}
        handled: set[bytes] = set()
        for i in range(len(patch_delta)):
            kb = patch_delta.keys[i].tobytes()
            if kb in seen or kb in handled:
                continue
            handled.add(kb)
            base_row = self.base.get_row(kb)
            if base_row is None:
                continue
            # old merged (reconstruct patch state before this commit's patch delta)
            old_patch: dict | None = None
            for j in range(len(patch_delta)):
                if patch_delta.keys[j].tobytes() == kb and patch_delta.diffs[j] < 0:
                    old_patch = {c: patch_delta.columns[c][j] for c in self.patch_cols}
            old_row = dict(base_row)
            if old_patch is not None:
                old_row.update(old_patch)
            new_row = self._merged(kb, base_row)
            if old_row != new_row:
                out_keys.append(patch_delta.keys[i])
                out_diffs.append(-1)
                out_rows.append(old_row)
                out_keys.append(patch_delta.keys[i])
                out_diffs.append(1)
                out_rows.append(new_row)
        return _delta_from_rows(out_keys, out_diffs, out_rows, self.output_columns).consolidated()


class _KeyPresenceMixin(Evaluator):
    """Shared machinery for intersect/difference/restrict/having."""

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.base = StateTable(self.output_columns)
        self.presence: List[set[bytes]] = [set() for _ in node.inputs[1:]]

    def _emit_row(self, kb: bytes, key: np.void, diff: int, row: dict, out: list) -> None:
        out.append((key, diff, row))

    def _condition(self, kb: bytes) -> bool:
        raise NotImplementedError

    def process(self, input_deltas: List[Delta]) -> Delta:
        base_delta = input_deltas[0]
        out: List[tuple] = []

        # update presence sets, recording transitions
        transitions: Dict[bytes, np.void] = {}
        for idx, delta in enumerate(input_deltas[1:]):
            for i in range(len(delta)):
                kb = delta.keys[i].tobytes()
                before = self._condition(kb)
                if delta.diffs[i] > 0:
                    self.presence[idx].add(kb)
                else:
                    self.presence[idx].discard(kb)
                after = self._condition(kb)
                if before != after:
                    transitions[kb] = delta.keys[i]

        for i in range(len(base_delta)):
            kb = base_delta.keys[i].tobytes()
            transitions.pop(kb, None)
        # base rows: emit if condition currently holds
        for i in range(len(base_delta)):
            kb = base_delta.keys[i].tobytes()
            if self._condition(kb):
                row = {c: base_delta.columns[c][i] for c in self.output_columns}
                out.append((base_delta.keys[i], int(base_delta.diffs[i]), row))
        self.base.apply(base_delta)

        for kb, key in transitions.items():
            row = self.base.get_row(kb)
            if row is None:
                continue
            diff = 1 if self._condition(kb) else -1
            out.append((key, diff, row))

        keys = [o[0] for o in out]
        diffs = [o[1] for o in out]
        rows = [o[2] for o in out]
        return _delta_from_rows(keys, diffs, rows, self.output_columns)


class IntersectEvaluator(_KeyPresenceMixin):
    def _condition(self, kb: bytes) -> bool:
        return all(kb in p for p in self.presence)


class DifferenceEvaluator(_KeyPresenceMixin):
    def _condition(self, kb: bytes) -> bool:
        return kb not in self.presence[0]


class RestrictEvaluator(_KeyPresenceMixin):
    def _condition(self, kb: bytes) -> bool:
        return kb in self.presence[0]


class HavingEvaluator(Evaluator):
    """Keep base rows whose key appears among the indexer pointer column's values."""

    _NON_STATE_ATTRS = Evaluator._NON_STATE_ATTRS + ("indexers",)

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.base = StateTable(self.output_columns)
        self.indexers: List[expr.ColumnReference] = node.config["indexers"]
        self.counts: List[Dict[bytes, int]] = [defaultdict(int) for _ in self.indexers]

    def _condition(self, kb: bytes) -> bool:
        return all(c.get(kb, 0) > 0 for c in self.counts)

    def process(self, input_deltas: List[Delta]) -> Delta:
        base_delta = input_deltas[0]
        out: List[tuple] = []
        transitions: Dict[bytes, np.void] = {}
        for idx, delta in enumerate(input_deltas[1:]):
            ref = self.indexers[idx]
            if len(delta) == 0:
                continue
            vals = delta.columns[ref.name]
            for i in range(len(delta)):
                p = vals[i]
                if not isinstance(p, Pointer):
                    continue
                kb = pointers_to_keys([p]).tobytes()
                before = self._condition(kb)
                self.counts[idx][kb] += int(delta.diffs[i])
                after = self._condition(kb)
                if before != after:
                    transitions[kb] = pointers_to_keys([p])[0]

        for i in range(len(base_delta)):
            kb = base_delta.keys[i].tobytes()
            transitions.pop(kb, None)
            if self._condition(kb):
                row = {c: base_delta.columns[c][i] for c in self.output_columns}
                out.append((base_delta.keys[i], int(base_delta.diffs[i]), row))
        self.base.apply(base_delta)

        for kb, key in transitions.items():
            row = self.base.get_row(kb)
            if row is None:
                continue
            diff = 1 if self._condition(kb) else -1
            out.append((key, diff, row))
        return _delta_from_rows(
            [o[0] for o in out], [o[1] for o in out], [o[2] for o in out], self.output_columns
        )


class WithUniverseOfEvaluator(Evaluator):
    def process(self, input_deltas: List[Delta]) -> Delta:
        return input_deltas[0]


class FlattenEvaluator(Evaluator):
    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return Delta.empty(self.output_columns)
        flat_name = self.node.config["flat_name"]
        origin_id = self.node.config.get("origin_id")
        out_keys, out_diffs, out_rows = [], [], []
        ptrs = keys_to_pointers(delta.keys)
        for i in range(len(delta)):
            value = delta.columns[flat_name][i]
            items = _iter_flatten(value)
            for j, item in enumerate(items):
                row = {c: delta.columns[c][i] for c in delta.column_names}
                row[flat_name] = item
                if origin_id:
                    row[origin_id] = ptrs[i]
                out_keys.append(pointer_from(ptrs[i], j, "flatten"))
                out_diffs.append(int(delta.diffs[i]))
                out_rows.append(row)
        return _delta_from_rows(
            pointers_to_keys(out_keys) if out_keys else [],
            out_diffs,
            out_rows,
            self.output_columns,
        )


def _iter_flatten(value: Any) -> list:
    from pathway_tpu.internals.json import Json

    if isinstance(value, Json):
        return [Json(v) if isinstance(v, (dict, list)) else v for v in value.value]
    if isinstance(value, (list, tuple)):
        return list(value)
    if isinstance(value, np.ndarray):
        return list(value)
    if isinstance(value, str):
        return list(value)
    raise TypeError(f"cannot flatten value of type {type(value).__name__}")


class IxEvaluator(Evaluator):
    """source-keyed lookup into target (reference ``ix``/``ix_ref``)."""

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.src_keys: Dict[bytes, bytes] = {}  # source key -> target key
        self.reverse: Dict[bytes, set[bytes]] = defaultdict(set)
        self.src_rows: Dict[bytes, np.void] = {}
        self.emitted: Dict[bytes, dict] = {}  # source key -> last emitted output row

    def process(self, input_deltas: List[Delta]) -> Delta:
        source_delta, target_delta = input_deltas
        source_table, target_table = self.node.inputs
        optional = self.node.config.get("optional", False)
        target_state = self.runner.state_of(target_table._node)
        out_keys, out_diffs, out_rows = [], [], []

        handled_sources: set[bytes] = set()
        if len(source_delta):
            resolver = self._resolver_for(source_table, source_delta)
            ixptrs = ee.evaluate(
                self.node.config["key_expression"], len(source_delta), resolver
            )
            for i in range(len(source_delta)):
                skb = source_delta.keys[i].tobytes()
                handled_sources.add(skb)
                d = int(source_delta.diffs[i])
                p = ixptrs[i]
                tkb = pointers_to_keys([p]).tobytes() if isinstance(p, Pointer) else None
                if d > 0:
                    self.src_keys[skb] = tkb
                    self.src_rows[skb] = source_delta.keys[i]
                    if tkb is not None:
                        self.reverse[tkb].add(skb)
                    row = None if tkb is None else target_state.get_row(tkb)
                    if row is None:
                        if not optional and tkb is not None:
                            raise KeyError(f"ix: missing key {p!r} in target table")
                        row = {c: None for c in self.output_columns}
                    self.emitted[skb] = row
                else:
                    self.src_keys.pop(skb, None)
                    self.src_rows.pop(skb, None)
                    if tkb is not None:
                        self.reverse[tkb].discard(skb)
                    # retraction replays what was last emitted, regardless of target state
                    row = self.emitted.pop(skb, {c: None for c in self.output_columns})
                out_keys.append(source_delta.keys[i])
                out_diffs.append(d)
                out_rows.append(row)

        # target-side changes re-emit affected source rows, preserving row-per-key:
        # optional sources flip between the real row and an all-None row
        none_row = {c: None for c in self.output_columns}
        for i in range(len(target_delta)):
            tkb = target_delta.keys[i].tobytes()
            d = int(target_delta.diffs[i])
            row = {c: target_delta.columns[c][i] for c in self.output_columns}
            for skb in self.reverse.get(tkb, set()):
                if skb in handled_sources:
                    continue
                prev = self.emitted.get(skb)
                if d > 0:
                    if prev is not None:
                        out_keys.append(self.src_rows[skb])
                        out_diffs.append(-1)
                        out_rows.append(prev)
                    out_keys.append(self.src_rows[skb])
                    out_diffs.append(1)
                    out_rows.append(row)
                    self.emitted[skb] = row
                else:
                    out_keys.append(self.src_rows[skb])
                    out_diffs.append(-1)
                    out_rows.append(prev if prev is not None else row)
                    if optional:
                        out_keys.append(self.src_rows[skb])
                        out_diffs.append(1)
                        out_rows.append(none_row)
                        self.emitted[skb] = none_row
                    else:
                        self.emitted.pop(skb, None)
        return _delta_from_rows(
            out_keys, out_diffs, out_rows, self.output_columns
        ).consolidated()


class SortEvaluator(Evaluator):
    """prev/next pointers per instance (reference ``prev_next.rs:770``)."""

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.rows: Dict[bytes, tuple] = {}  # key -> (sort_val, instance, Pointer)
        self.emitted: Dict[bytes, tuple] = {}  # key -> (prev, next)

    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return Delta.empty(self.output_columns)
        table = self.node.inputs[0]
        resolver = self._resolver_for(table, delta)
        n = len(delta)
        keys_vals = ee.evaluate(self.node.config["key"], n, resolver)
        instance_e = self.node.config.get("instance")
        instances = (
            ee.evaluate(instance_e, n, resolver) if instance_e is not None else np.zeros(n, dtype=object)
        )
        ptrs = keys_to_pointers(delta.keys)
        touched_instances = set()
        for i in range(n):
            kb = delta.keys[i].tobytes()
            if delta.diffs[i] > 0:
                self.rows[kb] = (keys_vals[i], instances[i], ptrs[i], delta.keys[i])
            else:
                self.rows.pop(kb, None)
            touched_instances.add(_hashable_scalar(instances[i]))

        # recompute orders for touched instances
        out_keys, out_diffs, out_rows = [], [], []
        by_instance: Dict[Any, list] = defaultdict(list)
        for kb, (sv, inst, ptr, key) in self.rows.items():
            hi = _hashable_scalar(inst)
            if hi in touched_instances:
                by_instance[hi].append((sv, ptr, kb, key))
        new_links: Dict[bytes, tuple] = {}
        for inst, rows in by_instance.items():
            rows.sort(key=lambda r: (r[0], r[1]))
            for idx, (sv, ptr, kb, key) in enumerate(rows):
                prev_ptr = rows[idx - 1][1] if idx > 0 else None
                next_ptr = rows[idx + 1][1] if idx < len(rows) - 1 else None
                new_links[kb] = (prev_ptr, next_ptr, key)
        # diff against emitted
        for kb, (pv, nv) in list(self.emitted.items()):
            if kb not in self.rows:
                # row gone: retract
                out_keys.append(self._key_of(kb))
                out_diffs.append(-1)
                out_rows.append({"prev": pv, "next": nv})
                del self.emitted[kb]
        for kb, (pv, nv, key) in new_links.items():
            old = self.emitted.get(kb)
            if old == (pv, nv):
                continue
            if old is not None:
                out_keys.append(key)
                out_diffs.append(-1)
                out_rows.append({"prev": old[0], "next": old[1]})
            out_keys.append(key)
            out_diffs.append(1)
            out_rows.append({"prev": pv, "next": nv})
            self.emitted[kb] = (pv, nv)
        return _delta_from_rows(out_keys, out_diffs, out_rows, self.output_columns)

    def _key_of(self, kb: bytes) -> np.void:
        arr = np.frombuffer(kb, dtype=KEY_DTYPE)
        return arr[0]


def _hashable_scalar(v: Any) -> Any:
    if isinstance(v, np.ndarray):
        return (v.tobytes(), v.shape)
    return v


class RemoveErrorsEvaluator(Evaluator):
    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return delta
        mask = np.ones(len(delta), dtype=bool)
        for col in delta.columns.values():
            if col.dtype == object:
                mask &= ~np.frompyfunc(lambda v: isinstance(v, Error), 1, 1)(col).astype(bool)
        return delta.select(mask)


class AsofNowEvaluator(Evaluator):
    """``_forget_immediately`` / ``_filter_out_results_of_forgetting``.

    Forget mode passes each commit's rows through unchanged and schedules a retraction of
    every insert; the runner drains those in the same commit's *neu* phase (the
    reference's odd-time forgetting, ``dataflow.rs:3447``): downstream state shrinks, but
    the forgetting filter drops neu deltas so delivered results stay. An upstream
    retraction of a still-scheduled key cancels the schedule (no double retraction).
    """

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.pending: Dict[bytes, tuple] = {}  # kb -> (key, row)

    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        mode = self.node.config["mode"]
        if mode == "filter_forgotten":
            if delta.neu:
                return Delta.empty(self.output_columns)
            return delta
        # forget mode
        for i in range(len(delta)):
            kb = delta.keys[i].tobytes()
            if delta.diffs[i] > 0:
                self.pending[kb] = (
                    delta.keys[i],
                    {c: delta.columns[c][i] for c in delta.column_names},
                )
            else:
                # genuine upstream retraction passes through; cancel the scheduled one
                self.pending.pop(kb, None)
        return delta

    def neu_pending(self) -> bool:
        return self.node.config["mode"] == "forget" and bool(self.pending)

    def drain_neu(self, input_deltas: List[Delta]) -> Delta:
        parts = []
        if self.pending:
            keys = [p[0] for p in self.pending.values()]
            rows = [p[1] for p in self.pending.values()]
            self.pending = {}
            parts.append(
                _delta_from_rows(keys, [-1] * len(keys), rows, self.output_columns)
            )
        if any(len(d) for d in input_deltas):
            parts.append(self.process(input_deltas))
        return Delta.concat(parts, self.output_columns)

    def has_pending(self) -> bool:
        return bool(self.pending)


class _TimeThresholdEvaluator(Evaluator):
    """Shared machinery for buffer/forget/freeze (reference ``time_column.rs``).

    Tracks ``now`` = the max value of the time column observed so far; a row is *ripe*
    once its threshold column value is ≤ ``now`` (the commit-granularity stand-in for
    the reference's frontier comparison). Ripeness scans use a min-heap on threshold so
    each commit pops only the ripe prefix (no full rescan of buffered state).
    """

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.now: Any = None
        self._heap: List[tuple] = []  # (threshold, seq, kb)
        self._heap_seq = 0

    def _thresholds_times(self, delta: Delta) -> Tuple[np.ndarray, np.ndarray]:
        table = self.node.inputs[0]
        resolver = self._resolver_for(table, delta)
        n = len(delta)
        thr = ee.evaluate(self.node.config["threshold"], n, resolver)
        tim = ee.evaluate(self.node.config["time"], n, resolver)
        return thr, tim

    def _advance_now(self, tim: np.ndarray, diffs: np.ndarray) -> None:
        for i in range(len(tim)):
            if diffs[i] > 0 and tim[i] is not None:
                if self.now is None or tim[i] > self.now:
                    self.now = tim[i]

    def _ripe(self, threshold: Any) -> bool:
        return self.now is not None and threshold <= self.now

    def _heap_push(self, threshold: Any, kb: bytes) -> None:
        import heapq

        heapq.heappush(self._heap, (threshold, self._heap_seq, kb))
        self._heap_seq += 1

    def _heap_pop_ripe(self, *, all_: bool = False):
        """Yield (threshold, kb) for entries whose threshold passed ``now`` (or all,
        when draining). Entries are lazily validated by the caller."""
        import heapq

        while self._heap and (all_ or self._ripe(self._heap[0][0])):
            threshold, _, kb = heapq.heappop(self._heap)
            yield threshold, kb


class BufferEvaluator(_TimeThresholdEvaluator):
    """Postpone emission until the stream's time passes each row's threshold
    (reference ``TimeColumnBuffer`` / ``postpone_core``, ``time_column.rs:255,380``).
    At stream close every buffered row flushes, as when the frontier empties."""

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        # kb -> [key, row, threshold, accumulated diff]
        self.pending: Dict[bytes, list] = {}
        self.emitted: set[bytes] = set()

    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        out_keys: List[Any] = []
        out_diffs: List[int] = []
        out_rows: List[dict] = []
        if len(delta):
            thr, tim = self._thresholds_times(delta)
            self._advance_now(tim, delta.diffs)
            for i in range(len(delta)):
                kb = delta.keys[i].tobytes()
                d = int(delta.diffs[i])
                row = {c: delta.columns[c][i] for c in delta.column_names}
                if d < 0 and kb in self.emitted:
                    # retraction of an already-emitted row passes straight through
                    out_keys.append(delta.keys[i])
                    out_diffs.append(-1)
                    out_rows.append(row)
                    self.emitted.discard(kb)
                    continue
                cur = self.pending.get(kb)
                if cur is None:
                    self.pending[kb] = [delta.keys[i], row, thr[i], d]
                    self._heap_push(thr[i], kb)
                else:
                    cur[3] += d
                    if d > 0:
                        cur[1] = row
                        if cur[2] != thr[i]:
                            cur[2] = thr[i]
                            self._heap_push(thr[i], kb)
                    if cur[3] == 0:
                        del self.pending[kb]
        draining = getattr(self.runner, "draining", False)
        for threshold, kb in self._heap_pop_ripe(all_=draining):
            cur = self.pending.get(kb)
            if cur is None or cur[2] != threshold:
                continue  # stale heap entry (row cancelled or rescheduled)
            del self.pending[kb]
            key, row, _, acc = cur
            if acc == 0:
                continue
            out_keys.append(key)
            out_diffs.append(acc)
            out_rows.append(row)
            if acc > 0:
                self.emitted.add(kb)
        return _delta_from_rows(
            out_keys, out_diffs, out_rows, self.output_columns
        ).consolidated()

    def has_pending(self) -> bool:
        return bool(self.pending)


class FreezeEvaluator(_TimeThresholdEvaluator):
    """Drop late rows — updates arriving after the stream's time passed their threshold
    (reference ``TimeColumnFreeze`` / ``ignore_late``, ``time_column.rs:631,677``)."""

    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return Delta.empty(self.output_columns)
        thr, tim = self._thresholds_times(delta)
        mask = np.ones(len(delta), dtype=bool)
        for i in range(len(delta)):
            if self._ripe(thr[i]):
                mask[i] = False
        self._advance_now(tim, delta.diffs)
        return delta.select(mask)


class ForgetEvaluator(_TimeThresholdEvaluator):
    """Retract rows once the stream's time passes their threshold (reference
    ``TimeColumnForget``, ``time_column.rs:556``). The retractions drain in the same
    commit's *neu* phase; with keep_results=True a downstream forgetting filter drops
    them so state is bounded but delivered results stay, and with keep_results=False
    there is no filter, so results are genuinely removed."""

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.live: Dict[bytes, tuple] = {}  # kb -> (key, row, threshold)
        self.pending_forget: Dict[bytes, tuple] = {}  # kb -> (key, row)

    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return Delta.empty(self.output_columns)
        thr, tim = self._thresholds_times(delta)
        self._advance_now(tim, delta.diffs)
        for i in range(len(delta)):
            kb = delta.keys[i].tobytes()
            if delta.diffs[i] > 0:
                row = {c: delta.columns[c][i] for c in delta.column_names}
                self.live[kb] = (delta.keys[i], row, thr[i])
                self._heap_push(thr[i], kb)
            else:
                # genuine upstream retraction: cancel any scheduled forgetting
                self.live.pop(kb, None)
                self.pending_forget.pop(kb, None)
        for threshold, kb in self._heap_pop_ripe():
            cur = self.live.get(kb)
            if cur is None or cur[2] != threshold:
                continue  # stale heap entry
            del self.live[kb]
            self.pending_forget[kb] = (cur[0], cur[1])
        return delta

    def neu_pending(self) -> bool:
        return bool(self.pending_forget)

    def drain_neu(self, input_deltas: List[Delta]) -> Delta:
        parts = []
        if self.pending_forget:
            keys = [p[0] for p in self.pending_forget.values()]
            rows = [p[1] for p in self.pending_forget.values()]
            self.pending_forget = {}
            parts.append(
                _delta_from_rows(keys, [-1] * len(keys), rows, self.output_columns)
            )
        if any(len(d) for d in input_deltas):
            parts.append(self.process(input_deltas))
        return Delta.concat(parts, self.output_columns)

    def has_pending(self) -> bool:
        return bool(self.pending_forget)


class ExternalIndexEvaluator(Evaluator):
    """External index operator (reference ``external_index.rs:38``).

    In as-of-now mode (the default, reference ``use_external_index_as_of_now``) a query is
    answered once against the index state at arrival and never revisited. With
    ``asof_now=False`` live queries are *re-answered* whenever the index changes: the old
    reply is retracted and the fresh one emitted (reference full differential semantics of
    ``DataIndex.query``)."""

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.index = node.config["index_factory"].make_instance()
        self.replies = StateTable(["_pw_index_reply"])
        self.asof_now: bool = bool(self.node.config.get("asof_now", True))
        # kb -> (key, qvec, limit, filter) for re-answering mode
        self.live_queries: Dict[bytes, tuple] = {}

    def _search_batch(
        self, vecs: List[Any], limits: List[int], filters: List[Any]
    ) -> List[List[tuple]]:
        if not vecs:
            return []
        if hasattr(self.index, "search_many"):
            return self.index.search_many(vecs, limits, filters)
        return [
            self.index.search(v, l, f) for v, l, f in zip(vecs, limits, filters)
        ]

    def process(self, input_deltas: List[Delta]) -> Delta:
        index_delta, query_delta = input_deltas
        index_table, query_table = self.node.inputs
        index_changed = len(index_delta) > 0

        if len(index_delta):
            resolver = self._resolver_for(index_table, index_delta)
            vec_ref = self.node.config["index_column"]
            vectors = ee.evaluate(vec_ref, len(index_delta), resolver)
            filter_col = self.node.config.get("index_filter_data_column")
            filters = (
                ee.evaluate(filter_col, len(index_delta), resolver)
                if filter_col is not None
                else None
            )
            ptrs = keys_to_pointers(index_delta.keys)
            add_mask = index_delta.diffs > 0
            for i in range(len(index_delta)):
                if add_mask[i]:
                    self.index.add(
                        ptrs[i], vectors[i], filters[i] if filters is not None else None
                    )
                else:
                    self.index.remove(ptrs[i])

        out_keys, out_diffs, out_rows = [], [], []
        if len(query_delta):
            resolver = self._resolver_for(query_table, query_delta)
            qvecs = ee.evaluate(self.node.config["query_column"], len(query_delta), resolver)
            limit_col = self.node.config.get("query_responses_limit_column")
            limits = (
                ee.evaluate(limit_col, len(query_delta), resolver)
                if limit_col is not None
                else None
            )
            qfilter_col = self.node.config.get("query_filter_column")
            qfilters = (
                ee.evaluate(qfilter_col, len(query_delta), resolver)
                if qfilter_col is not None
                else None
            )
            q_kbs = key_bytes(query_delta.keys)
            ins = [i for i in range(len(query_delta)) if query_delta.diffs[i] > 0]
            ins_replies = self._search_batch(
                [qvecs[i] for i in ins],
                [int(limits[i]) if limits is not None else 1 for i in ins],
                [qfilters[i] if qfilters is not None else None for i in ins],
            )
            reply_of = dict(zip(ins, ins_replies))
            for i in range(len(query_delta)):
                kb = q_kbs[i]
                if query_delta.diffs[i] > 0:
                    limit = int(limits[i]) if limits is not None else 1
                    flt = qfilters[i] if qfilters is not None else None
                    reply = tuple(reply_of[i])
                    out_keys.append(query_delta.keys[i])
                    out_diffs.append(1)
                    out_rows.append({"_pw_index_reply": reply})
                    if not self.asof_now:
                        self.live_queries[kb] = (
                            query_delta.keys[i],
                            qvecs[i],
                            limit,
                            flt,
                        )
                else:
                    self.live_queries.pop(kb, None)
                    stored = self.replies.get_row(kb)
                    if stored is not None:
                        out_keys.append(query_delta.keys[i])
                        out_diffs.append(-1)
                        out_rows.append(stored)

        if not self.asof_now and index_changed and self.live_queries:
            answered = set(key_bytes(query_delta.keys))
            live = [
                (kb, entry)
                for kb, entry in self.live_queries.items()
                if kb not in answered
            ]
            live_replies = self._search_batch(
                [entry[1] for _, entry in live],
                [entry[2] for _, entry in live],
                [entry[3] for _, entry in live],
            )
            for (kb, (key, qvec, limit, flt)), matches in zip(live, live_replies):
                reply = tuple(matches)
                stored = self.replies.get_row(kb)
                if stored is not None and stored["_pw_index_reply"] == reply:
                    continue
                if stored is not None:
                    out_keys.append(key)
                    out_diffs.append(-1)
                    out_rows.append(stored)
                out_keys.append(key)
                out_diffs.append(1)
                out_rows.append({"_pw_index_reply": reply})
        delta = _delta_from_rows(out_keys, out_diffs, out_rows, ["_pw_index_reply"])
        self.replies.apply(delta)
        return delta


class OutputEvaluator(Evaluator):
    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.callback = node.config.get("callback")
        self.on_end = node.config.get("on_end")
        self.input_columns = node.inputs[0].column_names()

    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if (
            getattr(self.runner, "_inject", None) is not None
            and not getattr(self.runner, "replay_outputs", True)
        ):
            return Delta.empty([])  # journal replay with silent sinks
        if self.callback is not None and len(delta):
            ptrs = keys_to_pointers(delta.keys)
            time = self.runner.current_time
            names = self.input_columns
            cols = [list(delta.columns[c]) for c in names]  # one C pass per column
            additions = (delta.diffs > 0).tolist()
            callback = self.callback
            for ptr, is_add, *vals in zip(ptrs, additions, *cols):
                callback(
                    key=ptr, row=dict(zip(names, vals)), time=time, is_addition=is_add
                )
        return Delta.empty([])

    def finish(self) -> None:
        if self.on_end is not None:
            self.on_end()


def _delta_from_rows(
    keys: Any, diffs: List[int], rows: List[dict], column_names: List[str]
) -> Delta:
    if len(rows) == 0:
        return Delta.empty(column_names)
    if isinstance(keys, list):
        if keys and isinstance(keys[0], Pointer):
            keys = pointers_to_keys(keys)
        else:
            arr = np.empty(len(keys), dtype=KEY_DTYPE)
            for i, k in enumerate(keys):
                arr[i] = k
            keys = arr
    columns = {
        name: ee._tidy(objarray([r[name] for r in rows]))
        for name in column_names
    }
    return Delta(keys, np.array(diffs, dtype=np.int64), columns)


EVALUATORS: Dict[type, type] = {
    pg.InputNode: InputEvaluator,
    pg.RowwiseNode: RowwiseEvaluator,
    pg.FilterNode: FilterEvaluator,
    pg.ReindexNode: ReindexEvaluator,
    pg.ConcatNode: ConcatEvaluator,
    pg.GroupbyNode: GroupbyEvaluator,
    pg.DeduplicateNode: DeduplicateEvaluator,
    pg.JoinNode: JoinEvaluator,
    pg.UpdateRowsNode: UpdateRowsEvaluator,
    pg.UpdateCellsNode: UpdateCellsEvaluator,
    pg.IntersectNode: IntersectEvaluator,
    pg.DifferenceNode: DifferenceEvaluator,
    pg.RestrictNode: RestrictEvaluator,
    pg.HavingNode: HavingEvaluator,
    pg.WithUniverseOfNode: WithUniverseOfEvaluator,
    pg.FlattenNode: FlattenEvaluator,
    pg.IxNode: IxEvaluator,
    pg.SortNode: SortEvaluator,
    pg.RemoveErrorsNode: RemoveErrorsEvaluator,
    pg.AsofNowUpdateNode: AsofNowEvaluator,
    pg.BufferNode: BufferEvaluator,
    pg.ForgetNode: ForgetEvaluator,
    pg.FreezeNode: FreezeEvaluator,
    pg.ExternalIndexNode: ExternalIndexEvaluator,
    pg.OutputNode: OutputEvaluator,
}


def _register_iterate() -> None:
    from pathway_tpu.internals.iterate import IterateEvaluator, IterateResultEvaluator

    EVALUATORS[pg.IterateNode] = IterateEvaluator
    EVALUATORS[pg.IterateResultNode] = IterateResultEvaluator


_register_iterate()
