"""OpenTelemetry hooks.

Parity: reference ``src/engine/telemetry.rs`` (OTLP traces + metrics around runs) and
``graph_runner/telemetry.py`` (Python-side spans around graph build/run). Spans go
through the opentelemetry API; without a configured SDK they are no-ops, and operators
can attach any exporter by configuring the global tracer provider before ``pw.run``.
"""

from __future__ import annotations

import contextlib
from typing import Any, Iterator


def _tracer() -> Any:
    try:
        from opentelemetry import trace

        return trace.get_tracer("pathway_tpu")
    except Exception:
        return None


@contextlib.contextmanager
def span(name: str, **attributes: Any) -> Iterator[None]:
    tracer = _tracer()
    if tracer is None:
        yield
        return
    with tracer.start_as_current_span(name) as current:
        for key, value in attributes.items():
            try:
                current.set_attribute(key, value)
            except Exception:
                pass
        yield
