"""OpenTelemetry hooks.

Parity: reference ``src/engine/telemetry.rs`` (OTLP traces + metrics around runs) and
``graph_runner/telemetry.py`` (Python-side spans around graph build/run). Spans go
through the opentelemetry API; without a configured SDK they are no-ops, and operators
can attach any exporter by configuring the global tracer provider before ``pw.run``.

The opentelemetry import is deferred AND gated: importing ``opentelemetry.context``
scans every installed distribution's entry points (hundreds of file reads), so the
no-op default never pays it. Enable with ``PATHWAY_TELEMETRY=1`` (or by importing
``opentelemetry.sdk`` yourself before ``pw.run`` — an already-imported API is used).
"""

from __future__ import annotations

import contextlib
import os
import sys
from typing import Any, Iterator


def _tracer() -> Any:
    try:
        requested = os.environ.get("PATHWAY_TELEMETRY", "").lower() not in (
            "", "0", "false", "no", "off",
        )
        if "opentelemetry.trace" not in sys.modules and not requested:
            return None  # no SDK configured and not requested: stay no-op, import-free
        from opentelemetry import trace

        return trace.get_tracer("pathway_tpu")
    except Exception:
        return None


@contextlib.contextmanager
def span(name: str, **attributes: Any) -> Iterator[None]:
    tracer = _tracer()
    if tracer is None:
        yield
        return
    with tracer.start_as_current_span(name) as current:
        for key, value in attributes.items():
            try:
                current.set_attribute(key, value)
            except Exception:
                pass
        yield
