"""OpenTelemetry hooks.

Parity: reference ``src/engine/telemetry.rs`` (OTLP traces + metrics around runs) and
``graph_runner/telemetry.py`` (Python-side spans around graph build/run). Spans go
through the opentelemetry API; without a configured SDK they are no-ops, and operators
can attach any exporter by configuring the global tracer provider before ``pw.run``.

The opentelemetry import is deferred AND gated: importing ``opentelemetry.context``
scans every installed distribution's entry points (hundreds of file reads), so the
no-op default never pays it. Enable with ``PATHWAY_TELEMETRY=1`` (or by importing
``opentelemetry.sdk`` yourself before ``pw.run`` — an already-imported API is used).
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
from typing import Any, Dict, Iterator


def _telemetry_requested(module: str) -> bool:
    """One home for the enablement rule shared by traces and metrics: the
    PATHWAY_TELEMETRY env gate, or the relevant OTel module already imported
    (an operator wiring an SDK provider implies intent)."""
    requested = os.environ.get("PATHWAY_TELEMETRY", "").lower() not in (
        "", "0", "false", "no", "off",
    )
    return requested or module in sys.modules


def _tracer() -> Any:
    try:
        if not _telemetry_requested("opentelemetry.trace"):
            return None  # no SDK configured and not requested: stay no-op, import-free
        from opentelemetry import trace

        return trace.get_tracer("pathway_tpu")
    except Exception:
        return None


@contextlib.contextmanager
def span(name: str, **attributes: Any) -> Iterator[None]:
    tracer = _tracer()
    if tracer is None:
        yield
        return
    with tracer.start_as_current_span(name) as current:
        for key, value in attributes.items():
            try:
                current.set_attribute(key, value)
            except Exception:
                pass
        yield


# -- stage counters (always-on, in-process) ----------------------------------
#
# Lightweight cumulative counters/timings for hot-path stages (embed pipeline
# tokenize/dispatch/cache, batch-UDF evaluation). Unlike the OTel instruments
# below these are ALWAYS on: one dict add under a lock per *batch-level* event,
# cheap enough for the serving path, and readable in-process (the bench's
# embedpipe section and DocumentStore.statistics_query report them) without any
# exporter wiring. Keys are dotted stage names; ``*_s`` keys are cumulative
# seconds, everything else is a count.

_stage_lock = threading.Lock()
_stage_counters: Dict[str, float] = {}

#: THE registered stage-counter namespaces. Every ``stage_add``/``stage_timer``
#: /``stage_add_many`` literal must live under one of these prefixes — the
#: PWA205 telemetry-contract lint (``analysis/resources.py``) enforces it
#: statically, so a typo'd or forked counter name fails ``cli analyze
#: --runtime`` instead of silently diverging from the /metrics dashboards.
#: Adding a new subsystem = adding its prefix HERE (one home, greppable).
STAGE_NAMESPACES: "tuple[str, ...]" = (
    "autoscale.",   # closed-loop autoscaler decisions/flaps
    "brownout.",    # overload-degradation ladder rungs + quiesce
    "cluster.",     # mesh fences/rejoins/membership/reshard
    "embed.",       # embed pipeline, caches, encoder service (embed.svc.*)
    "eval.",        # batch-UDF evaluation
    "exchange.",    # per-peer traffic + barrier waits/stragglers
    "fuse.",        # whole-commit fusion planner/jit
    "index.",       # tiered IVF index: tier hits, prefetch, rebuild/swap
    "index.quant.", # int8 retrieval: rescore batches, recalibrations, audits
    "lint.",        # graph/runtime lint diagnostics
    "modelcheck.",  # deterministic schedule exploration
    "persist.",     # checkpoints, journal compaction
    "replica.",     # read-replica fleet: feed, follow, serve/shed, failover
    "rest.",        # REST admission/shed plane
    "trace.",       # distributed-tracing plane: spans, promotions, flushes
)

#: registered flight-recorder event kinds (``FlightRecorder.record_event``
#: literals) — same contract as STAGE_NAMESPACES, enforced by PWA205 so
#: post-mortem tooling keyed on these names cannot silently miss an event.
FLIGHT_EVENT_KINDS: "frozenset[str]" = frozenset({
    "autoscale",
    "barrier_timeout",
    "brownout",
    "chaos_checkpoint_kill",
    "chaos_kill",
    "chaos_quant_kill",
    "chaos_rebuild_kill",
    "chaos_replica_kill",
    "chaos_replica_lag",
    "chaos_replica_torn_bootstrap",
    "checkpoint",
    "checkpoint_deferred",
    "drained",
    "fence",
    "fence_broadcast",
    "fence_received",
    "fusion",
    "index_rebuild",
    "index_swap",
    "lint",
    "membership",
    "membership_applied",
    "membership_left",
    "modelcheck",
    "peer_stale",
    "preflight_refuse",
    "quant_swap",
    "rejoin",
    "rejoin_installed",
    "replica_bootstrap",
    "replica_failover",
    "replica_refused",
    "trace_flush",
})

#: registered distributed-tracing span kinds (``tracing.trace_span`` /
#: ``start``/``record_span`` literal first args) — same contract as
#: STAGE_NAMESPACES/FLIGHT_EVENT_KINDS, enforced by PWA205 so the merger and
#: critical-path tooling keyed on these kinds cannot silently miss a span.
TRACE_SPAN_KINDS: "frozenset[str]" = frozenset({
    "barrier",       # exchange barrier wait (carries straggler attribution)
    "checkpoint",    # coordinated checkpoint write inside a commit
    "coalesce",      # query-coalescer admission wait
    "commit",        # one engine commit (deterministic cross-rank trace id)
    "encode",        # encoder-service tick (links N parent query spans)
    "exchange",      # mesh delta receive (links the sender's commit span)
    "fused_region",  # one fused chain executed as a single program
    "operator",      # one evaluator run (synthesized from CommitProfile ops)
    "replica_apply", # replica applying a commit frame from the feed
    "replica_serve", # replica answering a read (links the commit it serves)
    "rest",          # one REST route invocation (X-Pathway-Trace in/out)
})


def stage_add(name: str, value: float = 1.0) -> None:
    """Add ``value`` to the cumulative counter ``name``."""
    with _stage_lock:
        _stage_counters[name] = _stage_counters.get(name, 0.0) + value


def stage_add_many(updates: Dict[str, float]) -> None:
    """Fold several counter increments under ONE lock acquisition — the
    exchange layer bumps bytes+frames+waits per barrier and must not pay a
    lock round-trip per key."""
    with _stage_lock:
        for name, value in updates.items():
            _stage_counters[name] = _stage_counters.get(name, 0.0) + value


@contextlib.contextmanager
def stage_timer(name: str) -> Iterator[None]:
    """Accumulate wall seconds under ``<name>_s`` and bump ``<name>_calls``."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        elapsed = time.perf_counter() - t0
        with _stage_lock:
            _stage_counters[name + "_s"] = _stage_counters.get(name + "_s", 0.0) + elapsed
            _stage_counters[name + "_calls"] = _stage_counters.get(name + "_calls", 0.0) + 1


def stage_snapshot(prefix: str | None = None) -> Dict[str, float]:
    """Copy of the counters (optionally only those under ``prefix``)."""
    with _stage_lock:
        if prefix is None:
            return dict(_stage_counters)
        return {k: v for k, v in _stage_counters.items() if k.startswith(prefix)}


def stage_reset(prefix: str | None = None) -> None:
    with _stage_lock:
        if prefix is None:
            _stage_counters.clear()
        else:
            for k in [k for k in _stage_counters if k.startswith(prefix)]:
                del _stage_counters[k]


# -- metrics (reference telemetry.rs:37-45: OTLP process mem/cpu + latency) -------


def _metrics_enabled() -> bool:
    return _telemetry_requested("opentelemetry.metrics")


class MetricsRecorder:
    """OpenTelemetry metric instruments around runs (reference
    ``telemetry.rs:37-45``: process memory/cpu observable gauges, input/output
    latency gauges, row counters @ the meter's export interval).

    Instruments go through the opentelemetry METRICS API: a no-op without a
    configured ``MeterProvider``; operators wire an OTLP (or any) exporter by
    setting the global provider before ``pw.run``. Process stats come from
    psutil, sampled by the SDK's observation callbacks — zero cost per commit.

    Process-wide SINGLETON (``MetricsRecorder.get``): instruments register on
    the global meter exactly once; repeated ``pw.run`` calls (notebooks, the
    export/import pattern) swap which run's ``ProberStats`` feeds the latency
    gauges instead of piling up duplicate instruments and leaked callbacks.
    """

    _instance: "MetricsRecorder | None" = None

    @classmethod
    def get(cls, prober_stats: Any = None) -> "MetricsRecorder":
        if cls._instance is None or (
            not cls._instance._enabled and _metrics_enabled()
        ):
            # telemetry may be switched on BETWEEN runs (notebooks): a disabled
            # cached instance rebuilds once enablement appears; an enabled one
            # is never rebuilt (instruments must register exactly once)
            cls._instance = cls()
        cls._instance._stats = prober_stats
        return cls._instance

    def __init__(self):
        self._enabled = False
        self._stats: Any = None  # the CURRENT run's ProberStats (gauges read it)
        self._commit_counter: Any = None
        self._input_counter: Any = None
        self._output_counter: Any = None
        self._latency_hist: Any = None
        if not _metrics_enabled():
            return
        try:
            from opentelemetry import metrics

            meter = metrics.get_meter("pathway_tpu")
            import psutil

            process = psutil.Process()
            # prime the cpu clock: cpu_percent(interval=None) measures SINCE
            # the previous call, so an unprimed first sample reports 0.0 for
            # the whole first export interval
            process.cpu_percent(interval=None)

            def _mem_cb(_options: Any) -> list:
                from opentelemetry.metrics import Observation

                return [Observation(process.memory_info().rss)]

            def _cpu_cb(_options: Any) -> list:
                from opentelemetry.metrics import Observation

                return [Observation(process.cpu_percent(interval=None))]

            def _input_latency_cb(_options: Any) -> list:
                from opentelemetry.metrics import Observation

                stats = self._stats
                if stats is None:
                    return []
                ms = stats.latencies_ms()[0]
                return [Observation(ms)] if ms >= 0 else []

            def _output_latency_cb(_options: Any) -> list:
                from opentelemetry.metrics import Observation

                stats = self._stats
                if stats is None:
                    return []
                ms = stats.latencies_ms()[1]
                return [Observation(ms)] if ms >= 0 else []

            meter.create_observable_gauge(
                "process.memory.usage", callbacks=[_mem_cb], unit="By",
                description="resident set size",
            )
            meter.create_observable_gauge(
                "process.cpu.utilization", callbacks=[_cpu_cb], unit="%",
            )
            meter.create_observable_gauge(
                "pathway.input.latency", callbacks=[_input_latency_cb], unit="ms",
            )
            meter.create_observable_gauge(
                "pathway.output.latency", callbacks=[_output_latency_cb], unit="ms",
            )
            self._commit_counter = meter.create_counter(
                "pathway.commits", description="commits processed"
            )
            self._input_counter = meter.create_counter(
                "pathway.input.rows", description="source rows ingested"
            )
            self._output_counter = meter.create_counter(
                "pathway.output.rows", description="rows delivered to sinks"
            )
            self._latency_hist = meter.create_histogram(
                "pathway.commit.duration", unit="s",
            )
            self._enabled = True
        except Exception:
            self._enabled = False

    def record_commit(self, input_rows: int, output_rows: int, duration_s: float) -> None:
        if not self._enabled:
            return
        try:
            self._commit_counter.add(1)
            if input_rows:
                self._input_counter.add(input_rows)
            if output_rows:
                self._output_counter.add(output_rows)
            self._latency_hist.record(duration_s)
        except Exception:
            pass
