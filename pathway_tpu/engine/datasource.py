"""Input sources feeding the commit loop.

Parity: reference connector framework (``src/connectors/mod.rs`` — input thread + poller +
commit ticks). Host-side by design: TPU engines keep IO on the host CPU and ship batched
columns to the device.
"""

from __future__ import annotations

import queue
import threading
import time as time_mod
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from pathway_tpu.engine.columnar import Delta
from pathway_tpu.internals.keys import KEY_DTYPE, Pointer, keys_from_values, pointers_to_keys, sequential_keys


class DataSource:
    """One input's event feed; ``next_batch`` is called once per commit."""

    def next_batch(self, column_names: List[str]) -> Delta:
        raise NotImplementedError

    def is_finished(self) -> bool:
        raise NotImplementedError

    def on_start(self) -> None:
        pass

    def on_stop(self) -> None:
        pass

    # -- persistence hooks (reference ``OffsetValue``, ``offset.rs:37``) ----

    def offset_state(self) -> dict:
        """Light resumable position, journaled every commit."""
        return {}

    def subject_state(self) -> Any:
        """Heavyweight scanner state (reference ``cached_object_storage.rs``); dumped
        at snapshot intervals only."""
        return None

    def restore(self, offset: dict, subject_state: Any, subject_consumed: int = 0) -> None:
        """Reposition so already-journaled events are not re-emitted after replay.
        ``subject_state`` (if any) corresponds to ``subject_consumed`` events having been
        delivered; the gap up to ``offset``'s count is skipped by re-push dedup."""


class StaticDataSource(DataSource):
    """All rows present at time 0 (batch mode)."""

    def __init__(self, rows: List[tuple], keys: np.ndarray | None = None, column_names: List[str] | None = None):
        # rows: list of dicts column->value OR tuples following column_names
        self._rows = rows
        self._keys = keys
        self._column_names = column_names
        self._done = False

    def on_start(self) -> None:
        # a fresh GraphRunner re-runs the whole graph (debug captures, repeated pw.run),
        # unless a persistence restore marked the rows as replayed — a one-shot flag so
        # later runs of the same graph without persistence still re-emit
        if getattr(self, "_restored_done", False):
            self._restored_done = False
        else:
            self._done = False

    def offset_state(self) -> dict:
        return {"done": self._done}

    def restore(self, offset: dict, subject_state: Any, subject_consumed: int = 0) -> None:
        # replayed journal already carries the rows; don't emit them again
        if offset.get("done"):
            self._done = True
            self._restored_done = True

    def next_batch(self, column_names: List[str]) -> Delta:
        if self._done:
            return Delta.empty(column_names)
        self._done = True
        n = len(self._rows)
        columns: Dict[str, np.ndarray] = {}
        for name in column_names:
            col = np.empty(n, dtype=object)
            for i, row in enumerate(self._rows):
                col[i] = row[name] if isinstance(row, dict) else row[self._column_names.index(name)]
            columns[name] = _tidy_col(col)
        if self._keys is None:
            keys = sequential_keys(0, n)
        else:
            keys = self._keys
        return Delta(keys, np.ones(n, dtype=np.int64), columns)

    def is_finished(self) -> bool:
        return self._done


class StreamingDataSource(DataSource):
    """Queue-fed source; a producer thread pushes (key, row, diff) events.

    Mirrors the reference's per-connector input thread + mpsc channel + poller drain
    (``connectors/mod.rs:461-529``).
    """

    _MAX_EVENTS_PER_COMMIT = 100_000  # reference drains <=100k entries/iteration

    def __init__(self, subject: Any = None, autocommit_ms: float | None = None):
        self.events: "queue.Queue[tuple]" = queue.Queue()
        self._finished = threading.Event()
        self._started = False
        self.subject = subject
        self._thread: threading.Thread | None = None
        self._autocommit_ms = autocommit_ms
        self._seq = 0
        # persistence: events consumed so far; on resume, deterministically re-pushed
        # events up to the journaled count are skipped (the "seek")
        self._consumed = 0
        self._skip = 0
        # latest in-band subject state marker: (state, consumed count when it arrived).
        # State rides the event queue, so it is ordered after exactly the events it
        # accounts for — no cross-thread snapshot races, no count misalignment.
        self._latest_state: tuple | None = None

    # producer API ----------------------------------------------------------

    def push(self, values: dict, key: Pointer | None = None, diff: int = 1) -> None:
        self.events.put(("data", key, values, diff))

    def push_state(self, state: Any) -> None:
        """Producer checkpoints its replay state in-band (after the events it covers)."""
        self.events.put(("state", state))

    def close(self) -> None:
        self.events.put(("eof",))

    # engine API ------------------------------------------------------------

    def on_start(self) -> None:
        if self.subject is not None and not self._started:
            self._started = True

            def runner() -> None:
                try:
                    self.subject.run(self)
                finally:
                    self.close()

            self._thread = threading.Thread(target=runner, daemon=True, name="pathway:connector")
            self._thread.start()

    def next_batch(self, column_names: List[str]) -> Delta:
        rows: List[tuple] = []
        deadline = time_mod.monotonic() + (self._autocommit_ms or 10) / 1000.0
        while len(rows) < self._MAX_EVENTS_PER_COMMIT:
            timeout = deadline - time_mod.monotonic()
            try:
                event = self.events.get(timeout=max(timeout, 0.001))
            except queue.Empty:
                break
            if event[0] == "eof":
                self._finished.set()
                break
            if event[0] == "state":
                self._latest_state = (event[1], self._consumed)
                continue
            _, key, values, diff = event
            if self._skip > 0:
                self._skip -= 1
                continue
            self._consumed += 1
            rows.append((key, values, diff))
            if time_mod.monotonic() > deadline and rows:
                break
        if not rows:
            return Delta.empty(column_names)
        n = len(rows)
        keys = np.empty(n, dtype=KEY_DTYPE)
        for i, (key, values, diff) in enumerate(rows):
            if key is None:
                key_arr = sequential_keys(self._seq, 1)
                self._seq += 1
                keys[i] = key_arr[0]
            else:
                keys[i] = pointers_to_keys([key])[0]
        diffs = np.array([r[2] for r in rows], dtype=np.int64)
        columns = {}
        for name in column_names:
            col = np.empty(n, dtype=object)
            for i, (_, values, _) in enumerate(rows):
                col[i] = values.get(name)
            columns[name] = _tidy_col(col)
        return Delta(keys, diffs, columns)

    def is_finished(self) -> bool:
        return self._finished.is_set() and self.events.empty()

    # -- persistence ---------------------------------------------------------

    def offset_state(self) -> dict:
        return {"consumed": self._consumed, "seq": self._seq}

    def subject_state(self) -> tuple | None:
        """Latest in-band (state, consumed-count) marker — already consistent, no copy."""
        return self._latest_state

    def restore(self, offset: dict, subject_state: Any, subject_consumed: int = 0) -> None:
        self._seq = offset.get("seq", 0)
        consumed = offset.get("consumed", 0)
        restored_to = 0
        sub_restore = getattr(self.subject, "restore", None)
        if sub_restore is not None and subject_state is not None:
            # the subject repositions to the dumped state, which accounts for exactly
            # subject_consumed delivered events; the gap dedups by skip-count
            sub_restore(subject_state)
            restored_to = subject_consumed
            self._latest_state = (subject_state, consumed)
        self._consumed = consumed
        self._skip = max(consumed - restored_to, 0)


def _tidy_col(col: np.ndarray) -> np.ndarray:
    from pathway_tpu.engine.expression_evaluator import _tidy

    return _tidy(col)
