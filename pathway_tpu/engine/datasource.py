"""Input sources feeding the commit loop.

Parity: reference connector framework (``src/connectors/mod.rs`` — input thread + poller +
commit ticks). Host-side by design: TPU engines keep IO on the host CPU and ship batched
columns to the device.
"""

from __future__ import annotations

import queue
import threading
import time as time_mod
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from pathway_tpu.engine.columnar import Delta
from pathway_tpu.internals.keys import KEY_DTYPE, Pointer, keys_from_values, pointers_to_keys, sequential_keys


class DataSource:
    """One input's event feed; ``next_batch`` is called once per commit."""

    def next_batch(self, column_names: List[str]) -> Delta:
        raise NotImplementedError

    def is_finished(self) -> bool:
        raise NotImplementedError

    def on_start(self) -> None:
        pass

    def on_stop(self) -> None:
        pass

    # -- persistence hooks (reference ``OffsetValue``, ``offset.rs:37``) ----

    def offset_state(self) -> dict:
        """Light resumable position + this frame's segment-state deltas, journaled every
        commit."""
        return {}

    def checkpoint_state_deltas(self) -> list | None:
        """Drained segment markers for operator checkpoints (compaction drops the
        journal frames that carried them)."""
        return None

    def restore(self, offset: dict, state_deltas: list, tail: dict | None) -> None:
        """Reposition so already-journaled events are not re-emitted after replay.

        ``state_deltas``: every segment-completion marker journaled so far, in order —
        the subject folds them back into its scan state. ``tail`` describes the segment
        whose processing straddled the crash: ``{"token", "fp", "count", "rows"}`` with
        ``rows`` = the journaled ``(key, values, diff)`` events not yet covered by any
        marker. On the matching segment's re-arrival the source either skips ``count``
        re-pushed events (fingerprint unchanged — deterministic re-push) or retracts
        ``rows`` first (segment changed while down)."""


class StaticDataSource(DataSource):
    """All rows present at time 0 (batch mode)."""

    def __init__(
        self,
        rows: List[tuple],
        keys: np.ndarray | None = None,
        column_names: List[str] | None = None,
        columns: Dict[str, np.ndarray] | None = None,
    ):
        # rows: list of dicts column->value OR tuples following column_names;
        # columns: pre-columnarized arrays built at graph construction (off the
        # run clock), taking precedence over rows
        self._rows = rows
        self._keys = keys
        self._column_names = column_names
        self._columns = columns
        self._done = False

    def on_start(self) -> None:
        # a fresh GraphRunner re-runs the whole graph (debug captures, repeated pw.run),
        # unless a persistence restore marked the rows as replayed — a one-shot flag so
        # later runs of the same graph without persistence still re-emit
        if getattr(self, "_restored_done", False):
            self._restored_done = False
        else:
            self._done = False

    def offset_state(self) -> dict:
        return {"done": self._done}

    def restore(self, offset: dict, state_deltas: list, tail: dict | None) -> None:
        # replayed journal already carries the rows; don't emit them again
        if offset.get("done"):
            self._done = True
            self._restored_done = True

    def next_batch(self, column_names: List[str]) -> Delta:
        if self._done:
            return Delta.empty(column_names)
        self._done = True
        n = len(self._rows)
        columns: Dict[str, np.ndarray] = {}
        for name in column_names:
            if self._columns is not None and name in self._columns:
                columns[name] = self._columns[name]
                continue
            col = np.empty(n, dtype=object)
            for i, row in enumerate(self._rows):
                col[i] = row[name] if isinstance(row, dict) else row[self._column_names.index(name)]
            columns[name] = _tidy_col(col)
        if self._keys is None:
            keys = sequential_keys(0, n)
        else:
            keys = self._keys
        return Delta(keys, np.ones(n, dtype=np.int64), columns)

    def is_finished(self) -> bool:
        return self._done


class StreamingDataSource(DataSource):
    """Queue-fed source; a producer thread pushes (key, row, diff) events.

    Mirrors the reference's per-connector input thread + mpsc channel + poller drain
    (``connectors/mod.rs:461-529``). Draining is NON-blocking — the commit loop wakes
    on a per-runner event when any producer pushes, so end-to-end latency is wake-up +
    one commit rather than a serial per-source poll window — while ``autocommit_ms``
    keeps its reference meaning as the commit-tick interval: a source releases its
    queued events at most once per window, so steady streams still coalesce into
    window-sized batches instead of commit-per-event.
    """

    _MAX_EVENTS_PER_COMMIT = 100_000  # reference drains <=100k entries/iteration

    # one process-wide wake signal plus per-runner events: a producer push wakes
    # EVERY registered commit loop (each clears only its own event, so concurrent
    # runners never consume each other's wakeups)
    WAKE = threading.Event()
    _RUNNER_EVENTS: "list[threading.Event]" = []
    _REG_LOCK = threading.Lock()

    @classmethod
    def register_runner(cls, event: "threading.Event") -> None:
        with cls._REG_LOCK:
            cls._RUNNER_EVENTS.append(event)

    @classmethod
    def unregister_runner(cls, event: "threading.Event") -> None:
        with cls._REG_LOCK:
            if event in cls._RUNNER_EVENTS:
                cls._RUNNER_EVENTS.remove(event)

    @classmethod
    def _wake_all(cls) -> None:
        cls.WAKE.set()
        for ev in list(cls._RUNNER_EVENTS):
            ev.set()

    def __init__(
        self,
        subject: Any = None,
        autocommit_ms: float | None = None,
        loopback: bool = False,
    ):
        self.events: "queue.Queue[tuple]" = queue.Queue()
        self._finished = threading.Event()
        self._started = False
        self.subject = subject
        # loop-back sources (AsyncTransformer) are fed by results of THIS graph:
        # they do not gate the primary end-of-input signal (runner fires stream-end
        # notifications once every non-loopback source drained)
        self.loopback = loopback
        self._thread: threading.Thread | None = None
        self._autocommit_ms = autocommit_ms
        self._seq = 0
        # persistence: events consumed so far (journaled events count as consumed on
        # resume); deterministically re-pushed events dedup via segment-scoped skips
        self._consumed = 0
        self._skip = 0
        # segment bookkeeping. Markers ride the event queue, so each is ordered after
        # exactly the events it accounts for — no cross-thread snapshot races.
        self._in_progress: dict | None = None  # {"token", "fp", "emitted"}
        self._frame_state_deltas: List[Any] = []  # drained this frame, journaled with it
        self._drained_state_deltas: List[Any] = []  # full drained marker history
        # armed at restore when the crash straddled a segment
        self._pending_resume: dict | None = None  # {"token", "fp", "count", "rows"}

    # producer API ----------------------------------------------------------

    def push(self, values: dict, key: Pointer | None = None, diff: int = 1) -> None:
        self.events.put(("data", key, values, diff))
        StreamingDataSource._wake_all()

    def push_begin(self, token: Any, fingerprint: Any) -> None:
        """Producer marks the start of a replayable segment (e.g. one file): ``token``
        identifies it, ``fingerprint`` changes iff a re-push of the segment would produce
        a different event sequence."""
        self.events.put(("begin", token, fingerprint))
        StreamingDataSource._wake_all()

    def push_state(self, state_delta: Any) -> None:
        """Producer checkpoints the just-finished segment in-band (after its events).
        The delta is journaled with the commit frame; on resume all deltas are folded
        back through ``subject.restore``. Ends the current engine batch so journal
        frames align with segment boundaries."""
        self.events.put(("state", state_delta))
        StreamingDataSource._wake_all()

    def push_barrier(self) -> None:
        """Producer signals one full scan pass: any still-unmatched crash-straddled
        segment is gone — its journaled tail events get retracted."""
        self.events.put(("barrier",))
        StreamingDataSource._wake_all()

    def close(self) -> None:
        self.events.put(("eof",))
        StreamingDataSource._wake_all()

    # engine API ------------------------------------------------------------

    def on_start(self) -> None:
        if self.subject is not None and not self._started:
            self._started = True

            def runner() -> None:
                # a connector-thread failure must surface in the engine loop, not
                # die silently with the thread (reference: connector errors
                # terminate the run or hit the error log per terminate_on_error)
                try:
                    self.subject.run(self)
                except BaseException as exc:  # noqa: BLE001
                    self.events.put(("error", exc))
                finally:
                    self.close()

            self._thread = threading.Thread(target=runner, daemon=True, name="pathway:connector")
            self._thread.start()

    def next_batch(self, column_names: List[str]) -> Delta:
        rows: List[tuple] = []
        self._frame_state_deltas = []
        now = time_mod.monotonic()
        if (
            now < getattr(self, "_next_commit_at", 0.0)
            and not self._finished.is_set()
            and self.events.qsize() < self._MAX_EVENTS_PER_COMMIT
        ):
            # inside the autocommit window: let events coalesce (the reference's
            # commit tick); eof and overfull queues release immediately. Serving
            # latency is bounded by the tick — the rest connector runs a 1 ms
            # tick so per-request overhead is wake + <=1 ms.
            return Delta.empty(column_names)
        deadline = now + (self._autocommit_ms or 10) / 1000.0
        while len(rows) < self._MAX_EVENTS_PER_COMMIT:
            try:
                event = self.events.get_nowait()
            except queue.Empty:
                break
            if event[0] == "eof":
                self._finished.set()
                break
            if event[0] == "error":
                # re-raise the connector thread's failure on the engine loop
                # (reference Connector error propagation; terminate_on_error and
                # error-log routing are applied by the evaluator/runner above us)
                self._finished.set()
                raise event[1]
            if event[0] == "begin":
                _, token, fp = event
                self._in_progress = {"token": token, "fp": fp, "emitted": 0}
                pending = self._pending_resume
                if pending is not None and token == pending["token"]:
                    self._pending_resume = None
                    if fp == pending["fp"]:
                        # unchanged segment: the re-push repeats the journaled tail.
                        # emitted continues from the journaled count so a second crash
                        # before the marker journals the full skip width
                        self._skip += pending["count"]
                        self._in_progress["emitted"] = pending["count"]
                    else:
                        # segment changed while down: undo its journaled partial events
                        rows.extend(
                            (key, values, -diff)
                            for key, values, diff in pending["rows"]
                        )
                        self._consumed += len(pending["rows"])
                continue
            if event[0] == "state":
                self._in_progress = None
                self._frame_state_deltas.append(event[1])
                self._drained_state_deltas.append(event[1])
                if len(self._drained_state_deltas) > 256:
                    fold = getattr(self.subject, "fold_state_deltas", None)
                    if fold is not None:
                        # lossless compaction keeps memory bounded by live state even
                        # when checkpointing is off
                        self._drained_state_deltas = list(
                            fold(self._drained_state_deltas)
                        )
                # end the batch: journal frames align with segment boundaries, so the
                # resume tail never spans more than one segment
                break
            if event[0] == "barrier":
                pending, self._pending_resume = self._pending_resume, None
                if pending is not None:
                    # straddled segment never re-appeared (deleted while down)
                    rows.extend(
                        (key, values, -diff) for key, values, diff in pending["rows"]
                    )
                    self._consumed += len(pending["rows"])
                continue
            _, key, values, diff = event
            if self._skip > 0:
                self._skip -= 1
                continue
            self._consumed += 1
            if self._in_progress is not None:
                self._in_progress["emitted"] += 1
            rows.append((key, values, diff))
            if time_mod.monotonic() > deadline and rows:
                break
        if not rows:
            return Delta.empty(column_names)
        # a released batch opens the next coalescing window: sustained streams
        # batch at the autocommit tick (reference commit_duration semantics)
        self._next_commit_at = time_mod.monotonic() + (self._autocommit_ms or 10) / 1000.0
        n = len(rows)
        keys = np.empty(n, dtype=KEY_DTYPE)
        for i, (key, values, diff) in enumerate(rows):
            if key is None:
                key_arr = sequential_keys(self._seq, 1)
                self._seq += 1
                keys[i] = key_arr[0]
            else:
                keys[i] = pointers_to_keys([key])[0]
        diffs = np.array([r[2] for r in rows], dtype=np.int64)
        columns = {}
        for name in column_names:
            col = np.empty(n, dtype=object)
            for i, (_, values, _) in enumerate(rows):
                col[i] = values.get(name)
            columns[name] = _tidy_col(col)
        return Delta(keys, diffs, columns)

    def is_finished(self) -> bool:
        return self._finished.is_set() and self.events.empty()

    # -- persistence ---------------------------------------------------------

    def checkpoint_state_deltas(self) -> list | None:
        if not self._drained_state_deltas:
            return None
        fold = getattr(self.subject, "fold_state_deltas", None)
        if fold is None:
            return list(self._drained_state_deltas)
        folded = fold(self._drained_state_deltas)
        # folding is lossless: prune the history so memory stays bounded by live state
        self._drained_state_deltas = list(folded)
        return folded

    def offset_state(self) -> dict:
        out: dict = {"consumed": self._consumed, "seq": self._seq}
        if self._frame_state_deltas:
            out["state_deltas"] = list(self._frame_state_deltas)
        if self._in_progress is not None:
            out["in_progress"] = dict(self._in_progress)
        return out

    def restore(self, offset: dict, state_deltas: list, tail: dict | None) -> None:
        self._seq = offset.get("seq", 0)
        consumed = offset.get("consumed", 0)
        self._consumed = consumed
        self._drained_state_deltas = list(state_deltas)
        sub_restore = getattr(self.subject, "restore", None)
        if sub_restore is not None and state_deltas:
            sub_restore(state_deltas)
        if tail is None:
            return
        if tail.get("token") is not None:
            # segment-aware subject: dedup/undo decided when the segment re-arrives
            # (or provably never does — see push_barrier)
            self._pending_resume = tail
        elif tail.get("has_markers"):
            # segment-aware subject with no in-flight segment at crash time: completed
            # segments won't be re-pushed (the folded state skips them); nothing to dedup
            self._skip = max(consumed - tail.get("covered", 0), 0)
        else:
            # markerless subject: the whole journaled history is deterministically
            # re-pushed from the start; skip all of it
            self._skip = consumed


def _tidy_col(col: np.ndarray) -> np.ndarray:
    from pathway_tpu.engine.expression_evaluator import _tidy

    return _tidy(col)
