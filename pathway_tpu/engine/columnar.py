"""Columnar keyed state + update-stream deltas — the engine's data plane.

This replaces the reference's differential-dataflow arrangements (``src/engine/dataflow.rs``
``Column``/``Table`` over DD collections) with a batch-incremental columnar design: a table's
materialized state is struct-of-arrays keyed by 128-bit keys; each commit moves a ``Delta``
(keys, +1/-1 diffs, column values) through the operator graph. Dense numeric columns promote to
jax arrays on the TPU for kernel work; boxed columns stay host-side numpy object arrays.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Mapping, Sequence

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.keys import KEY_DTYPE, Pointer, keys_to_pointers


class Error:
    """Singleton poisoned value (reference ``Value::Error``, ``value.rs:207``)."""

    _instance: "Error | None" = None

    def __new__(cls) -> "Error":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "Error"

    def __bool__(self) -> bool:
        # a poisoned cell must never silently coerce to True (filters would keep
        # rows whose predicate ERRORED — e.g. NULL comparisons); consumers that
        # can absorb Error check isinstance explicitly
        raise TypeError("Error value has no truth value")


ERROR = Error()


def empty_keys() -> np.ndarray:
    return np.empty(0, dtype=KEY_DTYPE)


def objarray(values: Sequence[Any]) -> np.ndarray:
    """1-D object array; safe for ndarray-valued cells (``np.array(list, dtype=object)``
    would silently build a 2-D array when elements are equal-length ndarrays)."""
    out = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        out[i] = v
    return out


def empty_column(dtype: dt.DType) -> np.ndarray:
    return np.empty(0, dtype=dtype.np_dtype)


@dataclass
class Delta:
    """A batch of row updates: parallel arrays of key, diff (+1 insert / -1 retract), values.

    Retraction rows carry the values being retracted so downstream stateful operators
    (groupby, joins) can subtract without a lookup.

    ``neu`` marks a delta emitted at an odd ("neu") logical time — the reference's alt/neu
    scheme (``dataflow.rs:3447``) used for *forgetting* retractions: downstream operators
    process them normally (state shrinks) but ``_filter_out_results_of_forgetting`` drops
    them so already-delivered outputs stay.
    """

    keys: np.ndarray  # (n,) KEY_DTYPE
    diffs: np.ndarray  # (n,) int64 in {+1, -1}
    columns: Dict[str, np.ndarray]  # each (n,)
    neu: bool = False

    def __post_init__(self) -> None:
        n = len(self.keys)
        assert len(self.diffs) == n
        for name, col in self.columns.items():
            assert len(col) == n, f"column {name!r} length {len(col)} != {n}"

    def __len__(self) -> int:
        return len(self.keys)

    @property
    def column_names(self) -> list[str]:
        return list(self.columns)

    @staticmethod
    def empty(column_names: Iterable[str]) -> "Delta":
        return Delta(
            keys=empty_keys(),
            diffs=np.empty(0, dtype=np.int64),
            columns={name: np.empty(0, dtype=object) for name in column_names},
        )

    def select(self, mask: np.ndarray) -> "Delta":
        return Delta(
            keys=self.keys[mask],
            diffs=self.diffs[mask],
            columns={name: col[mask] for name, col in self.columns.items()},
            neu=self.neu,
        )

    def with_columns(self, columns: Dict[str, np.ndarray]) -> "Delta":
        return Delta(keys=self.keys, diffs=self.diffs, columns=columns, neu=self.neu)

    def negated(self) -> "Delta":
        return Delta(keys=self.keys, diffs=-self.diffs, columns=self.columns, neu=self.neu)

    @staticmethod
    def concat(deltas: Sequence["Delta"], column_names: Sequence[str]) -> "Delta":
        deltas = [d for d in deltas if len(d)]
        if not deltas:
            return Delta.empty(column_names)
        neu = any(d.neu for d in deltas)
        if len(deltas) == 1:
            d = deltas[0]
            return Delta(d.keys, d.diffs, {n: d.columns[n] for n in column_names}, neu=neu)
        keys = np.concatenate([d.keys for d in deltas])
        diffs = np.concatenate([d.diffs for d in deltas])
        columns = {}
        for name in column_names:
            parts = [d.columns[name] for d in deltas]
            if any(p.dtype == object for p in parts):
                merged = np.empty(sum(len(p) for p in parts), dtype=object)
                offset = 0
                for p in parts:
                    merged[offset : offset + len(p)] = p
                    offset += len(p)
                columns[name] = merged
            else:
                columns[name] = np.concatenate(parts)
        return Delta(keys, diffs, columns, neu=neu)

    def consolidated(self) -> "Delta":
        """Cancel matching (+1, -1) rows with identical key+values within the batch.

        Rows are identified by (key, xxh3-128 content signature); the signature batch
        rides the native typed hasher (``keys_from_values``) and rows group through the
        native ``KeyIndex`` in O(n), so consolidation is one vectorized pass instead of
        a per-row token loop (the DD ``consolidate`` counterpart at commit granularity).
        A single-signed batch (pure inserts or pure retracts) can never cancel and
        passes through untouched."""
        if len(self) == 0:
            return self
        if (self.diffs > 0).all() or (self.diffs < 0).all():
            return self  # cancellation needs opposite signs
        from pathway_tpu.internals.keys import KEY_DTYPE as _KD
        from pathway_tpu.internals.keys import keys_from_values

        sig = keys_from_values(list(self.columns.values()))
        # mix the row key into the content fingerprint (both already xxh3-uniform):
        # the combined 128 bits identify (key, values) rows for grouping
        combo = np.zeros(len(self), dtype=_KD)
        if len(sig):
            combo["hi"] = self.keys["hi"] * np.uint64(0x9E3779B97F4A7C15) + sig["hi"]
            combo["lo"] = self.keys["lo"] * np.uint64(0xC2B2AE3D27D4EB4F) + sig["lo"]
        else:
            combo["hi"], combo["lo"] = self.keys["hi"], self.keys["lo"]
        from pathway_tpu.engine.index import KeyIndex

        grouper = KeyIndex(len(self))
        inverse, is_new = grouper.upsert(combo)
        n_groups = grouper.slot_bound()
        if n_groups == len(self):
            return self  # all rows distinct: nothing cancels
        net = np.zeros(n_groups, dtype=np.int64)
        np.add.at(net, inverse, self.diffs)
        # a fresh index assigns dense slots in first-appearance order, so the rows
        # flagged is_new ARE the per-slot first occurrences, already slot-ordered
        first_idx = np.nonzero(is_new)[0]
        keep = np.nonzero(net != 0)[0]
        idx = first_idx[keep]
        out = self.select(idx)
        out.diffs = net[keep]
        # expand |diff|>1 into repeated unit rows to preserve row-per-key invariants downstream
        if np.any(np.abs(out.diffs) > 1):
            reps = np.abs(out.diffs).astype(np.int64)
            signs = np.sign(out.diffs)
            idx2 = np.repeat(np.arange(len(out.diffs)), reps)
            out = Delta(
                keys=out.keys[idx2],
                diffs=np.repeat(signs, reps),
                columns={n: c[idx2] for n, c in out.columns.items()},
                neu=out.neu,
            )
        return out


def grow_column(col: np.ndarray, new_cap: int) -> np.ndarray:
    """Resize a slot-indexed value array, preserving dtype and contents."""
    out = np.empty(new_cap, dtype=col.dtype)
    out[: len(col)] = col
    if col.dtype == object:
        out[len(col) :] = None
    return out


def adopt_dtype(storage: np.ndarray, incoming: np.ndarray) -> np.ndarray:
    """Converge a slot column's dtype with an incoming delta column's dtype.

    Columns are typed by what actually flows through them (schema-driven upstream);
    a dtype conflict across commits demotes the storage to object — correctness
    over speed for heterogeneous streams."""
    if storage.dtype == incoming.dtype or incoming.dtype == object:
        if storage.dtype != object and incoming.dtype == object:
            return storage.astype(object)
        return storage
    if storage.dtype == object:
        return storage
    promoted = np.promote_types(storage.dtype, incoming.dtype)
    if promoted == storage.dtype:
        return storage
    try:
        return storage.astype(promoted)
    except (TypeError, ValueError):
        return storage.astype(object)


def set_cells(storage: np.ndarray, slots: Any, values: np.ndarray) -> np.ndarray:
    """Write ``values`` into ``storage[slots]``, converging dtypes; returns storage
    (possibly re-typed — callers must re-assign)."""
    storage = adopt_dtype(storage, np.asarray(values))
    try:
        storage[slots] = values
    except (TypeError, ValueError):
        storage = storage.astype(object)
        storage[slots] = values
    return storage


class StateTable:
    """Materialized keyed state: the arrangement replacement.

    Struct-of-arrays with SCHEMA-DRIVEN dtypes: each value column keeps the dtype of
    the deltas flowing through it (int64/float64/bool typed arrays; object only for
    strings/Json/ndarray cells), so downstream kernels gather typed batches without
    re-boxing. The key->slot map is the native open-addressing ``KeyIndex``
    (``csrc/pathway_native.cc``), replacing the reference's DD arrangement position
    lookup — ``apply``/``lookup`` are O(batch) C calls, never per-row Python.
    """

    def __init__(self, column_names: Sequence[str]):
        self.column_names = list(column_names)
        from pathway_tpu.engine.index import KeyIndex

        self._index = KeyIndex()
        self._capacity = 0
        self._keys = empty_keys()
        self._columns: Dict[str, np.ndarray] = {
            name: np.empty(0, dtype=object) for name in self.column_names
        }
        self._valid = np.empty(0, dtype=bool)

    def __len__(self) -> int:
        return len(self._index)

    def _ensure_capacity(self) -> None:
        bound = self._index.slot_bound()
        if bound <= self._capacity:
            return
        new_cap = max(16, self._capacity * 2, bound)
        keys = np.zeros(new_cap, dtype=KEY_DTYPE)
        keys[: self._capacity] = self._keys
        self._keys = keys
        valid = np.zeros(new_cap, dtype=bool)
        valid[: self._capacity] = self._valid
        self._valid = valid
        for name in self.column_names:
            self._columns[name] = grow_column(self._columns[name], new_cap)
        self._capacity = new_cap

    def apply(self, delta: Delta) -> None:
        n = len(delta)
        if n == 0:
            return
        retract = delta.diffs < 0
        ret_rows = np.nonzero(retract)[0]
        if len(ret_rows):
            slots = self._index.remove(delta.keys[ret_rows])
            missing = slots < 0
            if missing.any():
                i = int(ret_rows[np.nonzero(missing)[0][0]])
                raise KeyError(f"retraction of absent key {delta.keys[i]!r}")
            self._valid[slots] = False
            for name in self.column_names:
                col = self._columns[name]
                if col.dtype == object:
                    col[slots] = None  # release refs
        ins_rows = np.nonzero(~retract)[0]
        if len(ins_rows):
            if self._capacity == 0:
                # first allocation: column dtypes come from the first delta through
                # (schema-driven upstream), making the typed fast paths live
                for name in self.column_names:
                    self._columns[name] = np.empty(0, dtype=delta.columns[name].dtype)
            slots, is_new = self._index.upsert(delta.keys[ins_rows])
            if not is_new.all():
                i = int(ins_rows[np.nonzero(~is_new)[0][0]])
                raise KeyError(
                    f"duplicate key {keys_to_pointers(delta.keys[i:i+1])[0]!r}"
                )
            self._ensure_capacity()
            self._keys[slots] = delta.keys[ins_rows]
            self._valid[slots] = True
            for name in self.column_names:
                incoming = delta.columns[name]
                self._columns[name] = col = adopt_dtype(self._columns[name], incoming)
                try:
                    col[slots] = incoming[ins_rows]
                except (TypeError, ValueError):
                    # incompatible cell values for the typed column: demote to object
                    self._columns[name] = col = col.astype(object)
                    col[slots] = incoming[ins_rows]

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        """Row slots for keys; -1 when absent."""
        return self._index.lookup(keys)

    def contains(self, keys: np.ndarray) -> np.ndarray:
        return self.lookup(keys) >= 0

    def gather(self, name: str, slots: np.ndarray) -> np.ndarray:
        """Typed value batch for the given slots (callers mask absent rows)."""
        return self._columns[name][slots]

    def snapshot(self) -> Delta:
        """Current state as an insertion Delta (used for late subscribers / joins)."""
        slots = np.nonzero(self._valid)[0]
        return Delta(
            keys=self._keys[slots].copy(),
            diffs=np.ones(len(slots), dtype=np.int64),
            columns={name: self._columns[name][slots].copy() for name in self.column_names},
        )

    def reshard_partition(self, owner_of: Any) -> "Dict[int, tuple]":
        """Elastic membership handoff: partition the live rows by their new
        owner rank. ``owner_of(keys) -> int64 owners``. Returns
        ``{dest: (keys, diffs, columns)}`` — complete, disjoint partitions a
        fresh table rebuilds from via ``apply``."""
        snap = self.snapshot()
        if len(snap) == 0:
            return {}
        owners = np.asarray(owner_of(snap.keys))
        out: Dict[int, tuple] = {}
        for dest in np.unique(owners):
            sel = owners == dest
            out[int(dest)] = (
                snap.keys[sel],
                snap.diffs[sel],
                {name: col[sel] for name, col in snap.columns.items()},
            )
        return out

    def reshard_partition_chunks(
        self, owner_of: Any, max_rows: int
    ) -> "Any":
        """Bounded-memory variant of :meth:`reshard_partition`: yields
        ``(dest, (keys, diffs, columns))`` pieces of at most ``max_rows``
        rows, copying one piece at a time instead of snapshotting the whole
        table — the streamed-handoff path's peak is O(piece), not O(state).
        Pieces for one dest are disjoint row ranges; a fresh table rebuilds
        from them via incremental ``apply`` in any order."""
        step = max(1, int(max_rows))
        slots = np.nonzero(self._valid)[0]
        if len(slots) == 0:
            return
        owners = np.asarray(owner_of(self._keys[slots]))
        for dest in np.unique(owners):
            dslots = slots[owners == dest]
            for s in range(0, len(dslots), step):
                piece = dslots[s : s + step]
                yield int(dest), (
                    self._keys[piece].copy(),
                    np.ones(len(piece), dtype=np.int64),
                    {
                        name: self._columns[name][piece].copy()
                        for name in self.column_names
                    },
                )

    def state_blob(self) -> bytes:
        """Compact picklable snapshot (live rows only) for operator checkpoints."""
        import pickle

        snap = self.snapshot()
        return pickle.dumps(
            (snap.keys, snap.diffs, snap.columns), protocol=pickle.HIGHEST_PROTOCOL
        )

    def load_state_blob(self, blob: bytes) -> None:
        import pickle

        keys, diffs, columns = pickle.loads(blob)
        self.__init__(self.column_names)
        self.apply(Delta(keys, diffs, columns))

    def get_row(self, key_b: bytes) -> dict[str, Any] | None:
        slot = int(self._index.lookup(np.frombuffer(key_b, dtype=KEY_DTYPE))[0])
        if slot < 0:
            return None
        return {name: self._columns[name][slot] for name in self.column_names}

    def column(self, name: str) -> np.ndarray:
        slots = np.nonzero(self._valid)[0]
        return self._columns[name][slots]

    def keys(self) -> np.ndarray:
        slots = np.nonzero(self._valid)[0]
        return self._keys[slots].copy()
