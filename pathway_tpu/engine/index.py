"""Native key index + multimap — the engine's slot allocators.

The reference engine resolves 128-bit row keys to arrangement positions inside
differential-dataflow's native trace structures (``src/engine/dataflow.rs`` arrangements
over ``Key`` fingerprints, ``src/engine/value.rs:41``). Here the equivalent is an
open-addressing C++ hash table (``csrc/pathway_native.cc`` ``KeyIndex``/``MultiMap``)
mapping a KEY_DTYPE batch to dense int64 *slots* in one call, so every stateful operator
(StateTable, groupby, joins) keeps its values in slot-indexed columnar arrays and never
touches a per-row Python dict on the hot path. When the native toolchain is unavailable,
dict-backed fallbacks preserve exact semantics.

Both structures pickle by content (live items), so operator checkpoints
(``persistence/engine.py``) remain portable across builds with and without the
native library.
"""

from __future__ import annotations

import ctypes
from typing import Iterable

import numpy as np

from pathway_tpu import native as _native
from pathway_tpu.internals.keys import KEY_DTYPE, key_bytes

_U64P = ctypes.POINTER(ctypes.c_uint64)
_I64P = ctypes.POINTER(ctypes.c_int64)
_U8P = ctypes.POINTER(ctypes.c_uint8)


def _key_ptr(keys: np.ndarray) -> "ctypes._Pointer":
    assert keys.dtype == KEY_DTYPE
    keys = np.ascontiguousarray(keys)
    return keys, keys.ctypes.data_as(_U64P)


class KeyIndex:
    """128-bit key -> dense slot map with slot recycling.

    Slots are assigned densely on insert and recycled on remove, so callers can
    maintain parallel value arrays sized to ``slot_bound()``.
    """

    def __new__(cls, capacity_hint: int = 16):
        if cls is KeyIndex:
            cls = _NativeKeyIndex if _native.get_lib() is not None else _PyKeyIndex
        return super().__new__(cls)

    # -- shared pickle protocol (content-based, implementation-portable) -----

    def __reduce__(self):
        keys, slots = self.items()
        return (_index_from_items, (keys, slots, self._next_slot_value()))

    def __len__(self) -> int:
        raise NotImplementedError

    def slot_bound(self) -> int:
        raise NotImplementedError

    def upsert(self, keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(slots, is_new) for a key batch; duplicates in one batch share a slot."""
        raise NotImplementedError

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def remove(self, keys: np.ndarray) -> np.ndarray:
        """Removed slot per key (-1 when absent); removed slots are recycled."""
        raise NotImplementedError

    def items(self) -> tuple[np.ndarray, np.ndarray]:
        raise NotImplementedError

    def _next_slot_value(self) -> int:
        raise NotImplementedError

    def _restore(self, keys: np.ndarray, slots: np.ndarray, next_slot: int) -> None:
        raise NotImplementedError


def _index_from_items(keys: np.ndarray, slots: np.ndarray, next_slot: int) -> KeyIndex:
    idx = KeyIndex(max(16, len(keys)))
    idx._restore(keys, slots, next_slot)
    return idx


class _NativeKeyIndex(KeyIndex):
    def __init__(self, capacity_hint: int = 16):
        self._lib = _native.get_lib()
        self._h = self._lib.pwtpu_idx_new(max(16, capacity_hint))

    def __del__(self) -> None:
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            lib.pwtpu_idx_free(h)
            self._h = None

    def __len__(self) -> int:
        return int(self._lib.pwtpu_idx_len(self._h))

    def slot_bound(self) -> int:
        return int(self._lib.pwtpu_idx_slot_bound(self._h))

    def upsert(self, keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        n = len(keys)
        keep, ptr = _key_ptr(keys)
        slots = np.empty(n, dtype=np.int64)
        is_new = np.empty(n, dtype=np.uint8)
        self._lib.pwtpu_idx_upsert(
            self._h, ptr, n, slots.ctypes.data_as(_I64P), is_new.ctypes.data_as(_U8P)
        )
        return slots, is_new.astype(bool)

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        n = len(keys)
        keep, ptr = _key_ptr(keys)
        slots = np.empty(n, dtype=np.int64)
        self._lib.pwtpu_idx_lookup(self._h, ptr, n, slots.ctypes.data_as(_I64P))
        return slots

    def remove(self, keys: np.ndarray) -> np.ndarray:
        n = len(keys)
        keep, ptr = _key_ptr(keys)
        slots = np.empty(n, dtype=np.int64)
        self._lib.pwtpu_idx_remove(self._h, ptr, n, slots.ctypes.data_as(_I64P))
        return slots

    def items(self) -> tuple[np.ndarray, np.ndarray]:
        n = len(self)
        keys = np.zeros(n, dtype=KEY_DTYPE)
        slots = np.empty(n, dtype=np.int64)
        if n:
            self._lib.pwtpu_idx_items(
                self._h,
                np.ascontiguousarray(keys).ctypes.data_as(_U64P),
                slots.ctypes.data_as(_I64P),
            )
        return keys, slots

    def _next_slot_value(self) -> int:
        return self.slot_bound()

    def _restore(self, keys: np.ndarray, slots: np.ndarray, next_slot: int) -> None:
        # slot ids index the caller's column arrays and must survive the pickle
        # round-trip exactly (checkpoints can contain recycled-slot gaps)
        keep, ptr = _key_ptr(keys)
        slots = np.ascontiguousarray(slots, dtype=np.int64)
        self._lib.pwtpu_idx_restore(
            self._h, ptr, slots.ctypes.data_as(_I64P), len(keys), next_slot
        )


class _PyKeyIndex(KeyIndex):
    """Dict-backed fallback with identical semantics."""

    def __init__(self, capacity_hint: int = 16):
        self._map: dict[bytes, int] = {}
        self._free: list[int] = []
        self._next = 0

    def __len__(self) -> int:
        return len(self._map)

    def slot_bound(self) -> int:
        return self._next

    def upsert(self, keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        n = len(keys)
        slots = np.empty(n, dtype=np.int64)
        is_new = np.zeros(n, dtype=bool)
        m = self._map
        for i, kb in enumerate(key_bytes(keys)):
            slot = m.get(kb)
            if slot is None:
                slot = self._free.pop() if self._free else self._alloc()
                m[kb] = slot
                is_new[i] = True
            slots[i] = slot
        return slots, is_new

    def _alloc(self) -> int:
        s = self._next
        self._next += 1
        return s

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        m = self._map
        out = np.empty(len(keys), dtype=np.int64)
        for i, kb in enumerate(key_bytes(keys)):
            out[i] = m.get(kb, -1)
        return out

    def remove(self, keys: np.ndarray) -> np.ndarray:
        out = np.empty(len(keys), dtype=np.int64)
        for i, kb in enumerate(key_bytes(keys)):
            slot = self._map.pop(kb, None)
            if slot is None:
                out[i] = -1
            else:
                out[i] = slot
                self._free.append(slot)
        return out

    def items(self) -> tuple[np.ndarray, np.ndarray]:
        n = len(self._map)
        keys = np.zeros(n, dtype=KEY_DTYPE)
        slots = np.empty(n, dtype=np.int64)
        for i, (kb, slot) in enumerate(self._map.items()):
            keys[i] = np.frombuffer(kb, dtype=KEY_DTYPE)[0]
            slots[i] = slot
        return keys, slots

    def _next_slot_value(self) -> int:
        return self._next

    def _restore(self, keys: np.ndarray, slots: np.ndarray, next_slot: int) -> None:
        for kb, slot in zip(key_bytes(keys), slots.tolist()):
            self._map[kb] = slot
        self._next = next_slot
        used = set(slots.tolist())
        self._free = [s for s in range(next_slot) if s not in used]


class MultiMap:
    """128-bit key -> bag of int64 values (join-key -> row slots).

    CONTRACT: values must be dense, non-negative, and unique across the whole
    map (each value in at most one bag at a time) — they are join-side row
    slots. The native implementation stores bags as intrusive linked lists over
    value-indexed arrays and silently corrupts chains if a value is inserted
    under two keys; the Python fallback is more permissive but callers must not
    rely on that."""

    def __new__(cls):
        if cls is MultiMap:
            cls = _NativeMultiMap if _native.get_lib() is not None else _PyMultiMap
        return super().__new__(cls)

    def __reduce__(self):
        keys, values = self.items()
        return (_mm_from_items, (keys, values))

    def insert(self, keys: np.ndarray, values: np.ndarray) -> None:
        raise NotImplementedError

    def remove(self, keys: np.ndarray, values: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def counts(self, keys: np.ndarray) -> tuple[np.ndarray, int]:
        raise NotImplementedError

    def probe(self, keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """CSR (offsets[n+1], matched_values) for a probe batch."""
        raise NotImplementedError

    def items(self) -> tuple[np.ndarray, np.ndarray]:
        raise NotImplementedError


def _mm_from_items(keys: np.ndarray, values: np.ndarray) -> MultiMap:
    mm = MultiMap()
    if len(keys):
        mm.insert(keys, values)
    return mm


class _NativeMultiMap(MultiMap):
    def __init__(self):
        self._lib = _native.get_lib()
        self._h = self._lib.pwtpu_mm_new()

    def __del__(self) -> None:
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            lib.pwtpu_mm_free(h)
            self._h = None

    def total(self) -> int:
        return int(self._lib.pwtpu_mm_total(self._h))

    def insert(self, keys: np.ndarray, values: np.ndarray) -> None:
        keep, ptr = _key_ptr(keys)
        values = np.ascontiguousarray(values, dtype=np.int64)
        self._lib.pwtpu_mm_insert(self._h, ptr, values.ctypes.data_as(_I64P), len(keys))

    def remove(self, keys: np.ndarray, values: np.ndarray) -> np.ndarray:
        keep, ptr = _key_ptr(keys)
        values = np.ascontiguousarray(values, dtype=np.int64)
        found = np.empty(len(keys), dtype=np.uint8)
        self._lib.pwtpu_mm_remove(
            self._h, ptr, values.ctypes.data_as(_I64P), len(keys),
            found.ctypes.data_as(_U8P),
        )
        return found.astype(bool)

    def counts(self, keys: np.ndarray) -> tuple[np.ndarray, int]:
        keep, ptr = _key_ptr(keys)
        counts = np.empty(len(keys), dtype=np.int64)
        total = self._lib.pwtpu_mm_count(self._h, ptr, len(keys), counts.ctypes.data_as(_I64P))
        return counts, int(total)

    def probe(self, keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        counts, total = self.counts(keys)
        offsets = np.zeros(len(keys) + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        values = np.empty(total, dtype=np.int64)
        if total:
            keep, ptr = _key_ptr(keys)
            self._lib.pwtpu_mm_fill(self._h, ptr, len(keys), values.ctypes.data_as(_I64P))
        return offsets, values

    def items(self) -> tuple[np.ndarray, np.ndarray]:
        n = self.total()
        keys = np.zeros(n, dtype=KEY_DTYPE)
        values = np.empty(n, dtype=np.int64)
        if n:
            self._lib.pwtpu_mm_items(
                self._h,
                np.ascontiguousarray(keys).ctypes.data_as(_U64P),
                values.ctypes.data_as(_I64P),
            )
        return keys, values


class _PyMultiMap(MultiMap):
    def __init__(self):
        self._map: dict[bytes, list[int]] = {}

    def total(self) -> int:
        return sum(len(v) for v in self._map.values())

    def insert(self, keys: np.ndarray, values: np.ndarray) -> None:
        m = self._map
        for kb, v in zip(key_bytes(keys), np.asarray(values, dtype=np.int64).tolist()):
            m.setdefault(kb, []).append(v)

    def remove(self, keys: np.ndarray, values: np.ndarray) -> np.ndarray:
        out = np.zeros(len(keys), dtype=bool)
        m = self._map
        for i, (kb, v) in enumerate(
            zip(key_bytes(keys), np.asarray(values, dtype=np.int64).tolist())
        ):
            bag = m.get(kb)
            if bag is None:
                continue
            try:
                idx = bag.index(v)
            except ValueError:
                continue
            bag[idx] = bag[-1]
            bag.pop()
            if not bag:
                del m[kb]
            out[i] = True
        return out

    def counts(self, keys: np.ndarray) -> tuple[np.ndarray, int]:
        m = self._map
        counts = np.empty(len(keys), dtype=np.int64)
        total = 0
        for i, kb in enumerate(key_bytes(keys)):
            c = len(m.get(kb, ()))
            counts[i] = c
            total += c
        return counts, total

    def probe(self, keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        counts, total = self.counts(keys)
        offsets = np.zeros(len(keys) + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        values = np.empty(total, dtype=np.int64)
        w = 0
        m = self._map
        for kb in key_bytes(keys):
            bag = m.get(kb)
            if bag:
                values[w : w + len(bag)] = bag
                w += len(bag)
        return offsets, values

    def items(self) -> tuple[np.ndarray, np.ndarray]:
        n = self.total()
        keys = np.zeros(n, dtype=KEY_DTYPE)
        values = np.empty(n, dtype=np.int64)
        j = 0
        for kb, bag in self._map.items():
            k = np.frombuffer(kb, dtype=KEY_DTYPE)[0]
            for v in bag:
                keys[j] = k
                values[j] = v
                j += 1
        return keys, values
