"""Per-operator commit profiles, log-bucketed histograms, and the flight recorder.

The metrics plane in three pieces, all stdlib-only and always importable:

- :class:`LogHistogram` — power-of-two log-bucketed latency histogram (p50/p95/
  p99 without numpy), shared by commit duration and REST/retrieve latency and
  rendered as valid OpenMetrics histogram families by ``ProberStats``;
- :class:`EngineProfiler` — process-wide per-operator totals (wall seconds,
  rows, retractions per node), fed one :class:`CommitProfile` per commit by
  ``GraphRunner._substep`` timings;
- :class:`FlightRecorder` — a bounded ring of the last N commit profiles plus
  cluster events (fence, rejoin, barrier timeout, chaos injections), dumped as
  JSON to the supervise dir on crash, fence, stall-kill, SIGTERM, or a chaos
  kill — the post-mortem answer to "what was the engine doing right before it
  died" without reproducing the failure.

Everything here is a leaf: no engine imports, one lock per structure, and every
dump path swallows OSError — observability must never kill the worker.

Env knobs: ``PATHWAY_PROFILE=0`` disables per-operator timing (the bench's
``telemetry`` section measures the on/off delta); ``PATHWAY_FLIGHT_RECORDER=0``
disables the recorder; ``PATHWAY_FLIGHT_RECORDER_DIR`` overrides the dump
directory (default: the supervise dir); ``PATHWAY_FLIGHT_RECORDER_COMMITS``
sizes the profile ring (default 64).
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional

# -- log-bucketed histogram ---------------------------------------------------

# power-of-two bucket bounds spanning ~1 µs .. 64 s: latencies below/above
# land in the first/overflow bucket. 27 finite bounds keeps the OpenMetrics
# exposition small enough to scrape every second.
_MIN_EXP = -20  # 2**-20 s ≈ 0.95 µs
_MAX_EXP = 6  # 2**6 s = 64 s


class LogHistogram:
    """Fixed power-of-two log buckets; O(1) observe, no dependencies.

    Quantiles interpolate log-linearly inside the winning bucket — accurate to
    a factor of 2**(1/count-in-bucket), plenty for p50/p95/p99 dashboards."""

    bounds = tuple(2.0**e for e in range(_MIN_EXP, _MAX_EXP + 1))

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # one slot per finite bound + the +Inf overflow slot
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def _bucket_of(self, value: float) -> int:
        if value <= self.bounds[0]:
            return 0
        if value > self.bounds[-1]:
            return len(self.bounds)
        # frexp: value = m * 2**e with m in [0.5, 1). A value in
        # (2**(k-1), 2**k] belongs to bound 2**k, so k = e unless the value is
        # exactly a power of two (m == 0.5, inclusive le bound): then k = e-1.
        m, e = math.frexp(value)
        k = e if m > 0.5 else e - 1
        return min(max(k - _MIN_EXP, 0), len(self.bounds) - 1)

    def observe(self, value: float) -> None:
        value = max(0.0, float(value))
        idx = self._bucket_of(value)
        with self._lock:
            self.counts[idx] += 1
            self.sum += value
            self.count += 1

    def quantile(self, q: float) -> float:
        """Value at quantile ``q`` in [0, 1]; 0.0 when empty."""
        with self._lock:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return 0.0
        target = q * total
        seen = 0
        for idx, n in enumerate(counts):
            if n == 0:
                continue
            if seen + n >= target:
                hi = self.bounds[idx] if idx < len(self.bounds) else self.bounds[-1] * 2
                lo = self.bounds[idx - 1] if 0 < idx <= len(self.bounds) else hi / 2
                frac = (target - seen) / n
                return lo * (hi / lo) ** frac
            seen += n
        return self.bounds[-1] * 2

    def percentiles(self) -> Dict[str, float]:
        return {
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
        }

    def openmetrics_lines(self, name: str, help_text: str) -> List[str]:
        """Render as one OpenMetrics histogram family (cumulative buckets,
        ``+Inf`` == ``_count``, ``_sum``)."""
        with self._lock:
            counts = list(self.counts)
            total = self.count
            value_sum = self.sum
        lines = [
            f"# HELP {name} {help_text}",
            f"# TYPE {name} histogram",
        ]
        cumulative = 0
        for bound, n in zip(self.bounds, counts):
            cumulative += n
            lines.append(f'{name}_bucket{{le="{bound!r}"}} {cumulative}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {total}')
        lines.append(f"{name}_count {total}")
        lines.append(f"{name}_sum {value_sum!r}")
        return lines

    def reset(self) -> None:
        with self._lock:
            self.counts = [0] * (len(self.bounds) + 1)
            self.sum = 0.0
            self.count = 0


_hist_lock = threading.Lock()
_histograms: Dict[str, LogHistogram] = {}


def histogram(name: str) -> LogHistogram:
    """Process-wide named histogram (created on first use). Names must be
    valid OpenMetrics metric names — they are exported verbatim."""
    with _hist_lock:
        h = _histograms.get(name)
        if h is None:
            h = _histograms[name] = LogHistogram()
        return h


def histograms() -> Dict[str, LogHistogram]:
    with _hist_lock:
        return dict(_histograms)


def autoscale_signals(input_rows: "int | None" = None) -> Dict[str, float]:
    """One worker's autoscale-signal sample for its supervisor status file
    (``parallel/autoscaler.py`` aggregates these across ranks and the
    controller diffs the cumulative counters between samples):

    - ``input_rows``   — cumulative source rows ingested (the rate signal;
      the runner passes its ProberStats total);
    - ``shed``         — cumulative shed requests (embed + REST admission);
    - ``barrier_wait_s`` — cumulative exchange barrier-wait seconds (the
      straggler/imbalance signal, attributed per peer on /metrics);
    - ``commit_p99_s`` — commit-duration p99 (0 while profiling is off);
    - ``brownout_level`` — the serving plane's engaged degradation rung.

    Cheap by construction: two dict snapshots and one histogram quantile —
    called at the status-file cadence (~4/s), never per row."""
    from pathway_tpu.engine import telemetry

    stages = telemetry.stage_snapshot()
    commit_hist = histograms().get("pathway_commit_duration_seconds")
    try:
        from pathway_tpu.engine.brownout import get_brownout

        brownout_level = get_brownout().level()
    except Exception:
        brownout_level = 0
    return {
        "input_rows": float(input_rows or 0),
        "shed": float(
            stages.get("embed.shed", 0.0) + stages.get("rest.shed", 0.0)
        ),
        "barrier_wait_s": float(stages.get("exchange.barrier_wait_s", 0.0)),
        "commit_p99_s": (
            float(commit_hist.quantile(0.99))
            if commit_hist is not None and commit_hist.count
            else 0.0
        ),
        "brownout_level": float(brownout_level),
    }


# -- per-commit profiles ------------------------------------------------------


class CommitProfile:
    """What one commit did: wall seconds overall and per evaluator.

    ``ops`` entries are ``(node_id, name, kind, seconds, rows, retractions,
    neu)`` tuples — one per evaluator run in ``GraphRunner._substep`` (the neu
    forgetting phase contributes separate entries with ``neu=True``). Fused
    chains (``engine/fusion.py``) contribute one REGION row per chain
    (``kind="fused_chain"``, real wall seconds) followed by per-member rows
    whose seconds are row-proportional estimates partitioning the region's
    time — so per-operator totals and the ``/metrics`` operator families stay
    live when a chain executes as a single program."""

    __slots__ = (
        "commit", "rank", "duration_s", "input_rows", "output_rows", "neu",
        "ts", "ts_mono", "ops",
    )

    def __init__(
        self,
        *,
        commit: int,
        rank: int,
        duration_s: float,
        input_rows: int,
        output_rows: int,
        neu: bool,
        ops: List[tuple],
    ):
        self.commit = commit
        self.rank = rank
        self.duration_s = duration_s
        self.input_rows = input_rows
        self.output_rows = output_rows
        self.neu = neu
        # dual stamp: wall for cross-rank merge, monotonic for ordering that
        # survives a wall-clock step mid-run (trace merger + post-mortems)
        self.ts = time.time()
        self.ts_mono = time.monotonic()
        self.ops = ops

    def slowest_op(self) -> Optional[tuple]:
        if not self.ops:
            return None
        return max(self.ops, key=lambda op: op[3])

    def as_dict(self) -> Dict[str, Any]:
        return {
            "commit": self.commit,
            "rank": self.rank,
            "duration_s": self.duration_s,
            "input_rows": self.input_rows,
            "output_rows": self.output_rows,
            "neu": self.neu,
            "ts": self.ts,
            "ts_mono": self.ts_mono,
            "ops": [
                {
                    "node": node_id,
                    "name": name,
                    "kind": kind,
                    "seconds": seconds,
                    "rows": rows,
                    "retractions": retractions,
                    "neu": neu,
                }
                for node_id, name, kind, seconds, rows, retractions, neu in self.ops
            ],
        }


class EngineProfiler:
    """Process-wide per-operator totals + the commit-duration histogram.

    One lock acquisition per COMMIT (``record_commit`` folds the whole
    profile), not per operator — the per-operator timing itself is lock-free
    in the commit loop."""

    #: fold cadence: the hot path only appends; every Nth commit (or any
    #: read) folds the pending profiles into the totals and the histogram
    _FOLD_EVERY = 64

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (node_id, name, kind) -> {"seconds", "rows", "retractions", "calls"}.
        # Keyed by the full triple, not node id alone: node ids restart at 0
        # for every graph built in this process (back-to-back runs, background
        # serving runners), and an id-only key would fold one graph's groupby
        # into another graph's input under the first comer's label.
        self._ops: Dict[tuple, Dict[str, Any]] = {}
        self._pending: List[CommitProfile] = []
        self.commits = 0
        self.commit_hist = histogram("pathway_commit_duration_seconds")

    def record_commit(self, profile: CommitProfile) -> None:
        """Hot path: one lock, one append. The dict folds and histogram
        observations are amortized over ``_FOLD_EVERY`` commits (readers fold
        first, so exports never lag)."""
        with self._lock:
            self.commits += 1
            self._pending.append(profile)
            if len(self._pending) >= self._FOLD_EVERY:
                self._fold_locked()

    def _fold_locked(self) -> None:
        pending, self._pending = self._pending, []
        for profile in pending:
            self.commit_hist.observe(profile.duration_s)
            for node_id, name, kind, seconds, rows, retractions, _neu in profile.ops:
                key = (node_id, name, kind)
                entry = self._ops.get(key)
                if entry is None:
                    entry = self._ops[key] = {
                        "seconds": 0.0,
                        "rows": 0,
                        "retractions": 0,
                        "calls": 0,
                    }
                entry["seconds"] += seconds
                entry["rows"] += rows
                entry["retractions"] += retractions
                entry["calls"] += 1

    def flush(self) -> None:
        """Fold any pending profiles (every reader calls this first)."""
        with self._lock:
            self._fold_locked()

    def operator_totals(self) -> List[Dict[str, Any]]:
        """Per-operator cumulative rows/seconds, sorted by node id."""
        with self._lock:
            self._fold_locked()
            return [
                {"node": node_id, "name": name, "kind": kind, **entry}
                for (node_id, name, kind), entry in sorted(self._ops.items())
            ]

    def snapshot(self) -> Dict[str, Any]:
        """The /v1/statistics shape: commit latency percentiles + the top
        operators by cumulative wall time."""
        ops = sorted(
            self.operator_totals(),  # folds pending first
            key=lambda e: e["seconds"],
            reverse=True,
        )
        pct = self.commit_hist.percentiles()
        return {
            "commits": self.commits,
            "commit_duration_ms": {k: v * 1000.0 for k, v in pct.items()},
            "operators": ops[:20],
        }

    def reset(self) -> None:
        with self._lock:
            self._ops = {}
            self._pending = []
            self.commits = 0
        self.commit_hist.reset()


_profiler = EngineProfiler()


def get_profiler() -> EngineProfiler:
    return _profiler


def profiling_enabled() -> bool:
    """Per-operator timing gate (the bench's telemetry section measures the
    delta this buys back when off)."""
    return os.environ.get("PATHWAY_PROFILE", "").lower() not in (
        "0", "false", "no", "off",
    )


# -- flight recorder ----------------------------------------------------------


class FlightRecorder:
    """Bounded ring of recent commit profiles + cluster events, dumped as JSON
    on the ways a worker dies (crash, fence, stall-kill, SIGTERM, chaos kill).

    The dump's ``summary`` is the one-line post-mortem the supervisor prints:
    last completed commit, the slowest operator of that commit, and the
    exchange barrier that was pending at death (if any)."""

    _EVENT_RING = 256

    def __init__(self) -> None:
        # RLock, not Lock: dump() runs from the SIGTERM signal handler on the
        # main thread, which may have been interrupted between the bytecodes
        # of a record_commit that holds this lock — a non-reentrant lock
        # would deadlock the handler and make the process ignore SIGTERM.
        # Handler-time state is bytecode-consistent (deque ops are single C
        # calls), so reentering for a read-only snapshot is safe.
        self._lock = threading.RLock()
        size = 64
        try:
            size = max(1, int(os.environ.get("PATHWAY_FLIGHT_RECORDER_COMMITS", "64")))
        except ValueError:
            pass
        self.enabled = os.environ.get("PATHWAY_FLIGHT_RECORDER", "").lower() not in (
            "0", "false", "no", "off",
        )
        self._profiles: "collections.deque[CommitProfile]" = collections.deque(
            maxlen=size
        )
        self._events: "collections.deque[Dict[str, Any]]" = collections.deque(
            maxlen=self._EVENT_RING
        )
        self.rank = 0
        self._default_dir: Optional[str] = None
        # exchange tags currently blocking in a barrier recv, PER THREAD
        # (PATHWAY_THREADS workers share this process-wide recorder and
        # barrier concurrently; one slot would cross-clobber). Plain dict
        # set/del keyed by thread id — GIL-atomic, no lock on the hot path.
        self._pending_barriers: Dict[int, str] = {}
        self.dumps = 0

    def configure(self, *, rank: int, default_dir: Optional[str]) -> None:
        self.rank = rank
        if default_dir is not None:
            self._default_dir = default_dir

    # -- hot-path hooks (cheap, lock only on ring append) ---------------------

    def record_commit(self, profile: CommitProfile) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._profiles.append(profile)

    def record_event(self, kind: str, **details: Any) -> None:
        if not self.enabled:
            return
        event = {"ts": time.time(), "ts_mono": time.monotonic(), "kind": kind}
        event.update(details)
        with self._lock:
            self._events.append(event)

    def note_barrier(self, tag: Optional[bytes]) -> None:
        """The exchange layer marks the tag this THREAD is about to block on
        (and clears it on success) so a dump can name the pending barrier(s)
        at death."""
        tid = threading.get_ident()
        if tag is None:
            self._pending_barriers.pop(tid, None)
        else:
            self._pending_barriers[tid] = tag.decode("utf-8", "replace")

    def _pending_barrier_summary(self) -> "Optional[str]":
        pending = sorted(set(dict(self._pending_barriers).values()))
        if not pending:
            return None
        return pending[0] if len(pending) == 1 else ", ".join(pending)

    # -- dumping --------------------------------------------------------------

    def _resolve_dir(self) -> Optional[str]:
        return os.environ.get("PATHWAY_FLIGHT_RECORDER_DIR") or self._default_dir

    def dump_path(self, directory: Optional[str] = None) -> Optional[str]:
        directory = directory or self._resolve_dir()
        if directory is None:
            return None
        return os.path.join(directory, f"flight-rank-{self.rank}.json")

    def payload(self, reason: str) -> Dict[str, Any]:
        with self._lock:
            profiles = [p.as_dict() for p in self._profiles]
            events = list(self._events)
        last = profiles[-1] if profiles else None
        slowest = None
        if last and last["ops"]:
            op = max(last["ops"], key=lambda o: o["seconds"])
            slowest = {
                "name": op["name"], "kind": op["kind"], "seconds": op["seconds"],
            }
        trace = None
        spans_fn = _trace_spans_fn
        if spans_fn is not None:
            try:
                trace = spans_fn()
            except Exception:
                trace = None  # observability must never kill the worker
        return {
            "reason": reason,
            "rank": self.rank,
            "pid": os.getpid(),
            "ts": time.time(),
            "ts_mono": time.monotonic(),
            "profiles": profiles,
            "events": events,
            "trace": trace,
            "summary": {
                "last_commit": last["commit"] if last else None,
                "slowest_operator": slowest,
                "pending_barrier": self._pending_barrier_summary(),
            },
        }

    def dump(self, reason: str, directory: Optional[str] = None) -> Optional[str]:
        """Write the ring to ``flight-rank-N.json`` (atomic rename); returns
        the path, or None when disabled / no dump dir is known. Never raises —
        a failing dump must not mask the failure being recorded."""
        if not self.enabled:
            return None
        path = self.dump_path(directory)
        if path is None:
            return None
        try:
            blob = json.dumps(self.payload(reason))
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(blob)
            os.replace(tmp, path)
            self.dumps += 1
        except (OSError, TypeError, ValueError):
            return None
        flush_fn = _trace_flush_fn
        if flush_fn is not None:
            try:
                # partial-trace guarantee: the jsonl flush rides every dump
                # path (crash, fence, SIGTERM, chaos kill) so a dead rank's
                # spans land next to its flight dump
                flush_fn(os.path.dirname(path), reason)
            except Exception:
                pass
        return path

    def reset(self) -> None:
        with self._lock:
            self._profiles.clear()
            self._events.clear()
        self._pending_barriers = {}
        self.dumps = 0


_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()

# tracing-plane hooks (registered by engine/tracing.py at tracer creation;
# function-valued module globals keep this module a leaf — no engine imports):
# _trace_spans_fn() -> recent-span payload embedded in every flight dump;
# _trace_flush_fn(directory, reason) flushes trace-rank-N.jsonl beside it.
_trace_spans_fn: Optional[Any] = None
_trace_flush_fn: Optional[Any] = None


def register_trace_hooks(spans_fn: Any, flush_fn: Any) -> None:
    global _trace_spans_fn, _trace_flush_fn
    _trace_spans_fn = spans_fn
    _trace_flush_fn = flush_fn


def get_flight_recorder() -> FlightRecorder:
    """Process-wide recorder (lazily built from the env): the engine feeds it
    profiles, the cluster/chaos layers feed it events, and any of them may
    trigger a dump."""
    global _recorder
    rec = _recorder
    if rec is None:
        with _recorder_lock:
            rec = _recorder
            if rec is None:
                rec = _recorder = FlightRecorder()
    return rec


def reset_profile() -> None:
    """Test/bench hook: clear the profiler, registered histograms, and the
    flight recorder ring (the recorder keeps its env-derived config)."""
    _profiler.reset()
    for h in histograms().values():
        h.reset()
    rec = _recorder
    if rec is not None:
        rec.reset()


def flight_summary_line(payload: Dict[str, Any]) -> str:
    """One-line human summary of a dump payload (shared by the supervisor's
    post-mortem and tests so the format has a single home)."""
    summary = payload.get("summary") or {}
    parts = [f"last commit {summary.get('last_commit')}"]
    slowest = summary.get("slowest_operator")
    if slowest:
        parts.append(
            f"slowest operator {slowest['name']} ({slowest['seconds'] * 1000:.1f} ms)"
        )
    pending = summary.get("pending_barrier")
    if pending:
        parts.append(f"pending barrier {pending}")
    reason = payload.get("reason")
    if reason:
        parts.append(f"reason {reason}")
    return ", ".join(parts)
