"""The commit loop — graph execution driver.

Parity: reference ``pw.run`` path (``internals/run.py`` → ``GraphRunner`` →
``run_with_new_dataflow_graph``'s worker loop ``dataflow.rs:5596-5650``). Instead of timely's
``step_or_park``, each commit gathers one batch per source, pushes deltas through the operator
DAG in topological order, and delivers outputs. Timestamps are even integers (data times), as in
the reference's alt/neu scheme (``timestamp.rs:20``).
"""

from __future__ import annotations

import os
import time as time_mod
from typing import Any, Dict, List, Optional

import numpy as np

from pathway_tpu.engine import tracing as _tracing
from pathway_tpu.engine.columnar import Delta, StateTable
from pathway_tpu.engine.profile import CommitProfile
from pathway_tpu.engine.profile import autoscale_signals as _autoscale_signals
from pathway_tpu.internals import parse_graph as pg


class GraphRunner:
    def __init__(self, graph: Any = None):
        self.graph = graph if graph is not None else pg.G
        self.states: Dict[int, StateTable] = {}
        self.evaluators: Dict[int, Any] = {}
        self.current_time = 0
        self._commit = 0
        self._sources: List[tuple] = []
        self._nodes: List[pg.Node] = []
        self._monitor: Any = None
        self._ready = False
        self.draining = False
        self._step_counts: Dict[int, int] = {}
        self._persistence: Any = None
        self._inject: Optional[Dict[int, Delta]] = None  # journal replay injection
        self._input_deltas: Dict[int, Delta] = {}
        self._graph_sig = ""
        self._snapshot_interval_s = 0.0
        self._last_checkpoint = time_mod.monotonic()
        self._warned_unpicklable = False
        self.prober_stats: Any = None
        self._output_rows_this_commit = 0
        self._http_server: Any = None
        self.replay_outputs = True
        self._shared_nonroot = False  # transparent-threads worker with rank > 0
        self._substep_deltas: Dict[int, Delta] = {}
        self._materialized: set = set()
        self._materialize_all = False  # nested iterate runners read states directly
        self._cluster: Any = None  # multi-process exchange (parallel/cluster.py)
        self._metrics: Any = None  # OTel MetricsRecorder (engine/telemetry.py)
        self._chaos: Any = None  # fault injection (internals/chaos.py), None when off
        self._rank = 0
        self._supervise_dir: Any = None  # PATHWAY_SUPERVISE_DIR (spawn supervisor)
        self._last_status_write = 0.0
        # surgical single-rank restart (epoch fencing; parallel/cluster.py)
        self._surgical = False  # PATHWAY_RESTART_MODE=surgical (spawn supervisor)
        self._rejoin_carry: Dict[int, Delta] = {}  # in-flight inputs saved over a fence
        self._input_deltas_commit = -1  # commit the current _input_deltas belong to
        self._rejoins = 0
        self._last_rejoin_s: "float | None" = None
        self._rejoin_state = "running"  # "running" | "fencing" | "rejoining"
        # metrics plane (engine/profile.py): per-operator commit profiles +
        # the crash/stall flight recorder; None in nested iterate runners
        self._profiler: Any = None
        self._recorder: Any = None
        self._profile_ops: "List[tuple] | None" = None
        self._last_commit_profile: "CommitProfile | None" = None
        # whole-commit fusion (engine/fusion.py): the substep schedule with
        # operator chains collapsed into compiled ChainPrograms; None = stock
        # per-node dispatch (PATHWAY_FUSION=off, nested runners, nothing fuses)
        self._fusion_schedule: "List[Any] | None" = None
        # one AnalysisContext per runner, shared by the lint gate and the
        # fusion planner (building it twice = two full DAG walks per pw.run)
        self._analysis_ctx: Any = None
        # coordinated cluster checkpoints (persistence/engine.py manifest
        # protocol) + incremental rewind (undo record + mesh serve log)
        self._ckpt_interval_s = 0.0  # 0 = coordinated checkpoints off
        self._ckpt_compact = True  # PATHWAY_CHECKPOINT_COMPACT=0 disables
        self._ckpt_disabled_reason: "str | None" = None
        self._manifest_commit: "int | None" = None  # last durable manifest
        self._undo_depth = 0  # PATHWAY_UNDO_RING_DEPTH; 0 = rewind rung off
        self._undo_max_bytes = 0  # PATHWAY_UNDO_MAX_STATE_BYTES; 0 = unbounded
        self._undo_current: "Dict[str, Any] | None" = None  # in-flight record
        # adaptive rewind-cost guard: EWMA of per-commit undo-capture seconds
        # vs whole-commit seconds — state_dict() re-pickles every touched
        # operator's state each commit, so a large-state graph under the byte
        # cap could still pay more for the rung than the tail replay it avoids
        self._undo_capture_ewma = 0.0
        self._undo_commit_ewma = 0.0
        self._undo_armed_commits = 0
        self._rewind_safe = True  # graph has no drain-sensitive operators
        # elastic mesh membership (parallel/membership.py): grow/shrink the
        # cluster under traffic via an epoch-fenced MEMBERSHIP_CHANGE
        # transition at a quiesced commit boundary
        self._membership_state = "stable"  # stable|joining|draining|resharding
        self._target_workers: "int | None" = None
        self._member_pending: Any = None  # agreed directive awaiting readiness
        self._member_all_ready = False
        self._member_done_gen = -1  # newest applied/refused/failed generation
        self._member_refused: "tuple | None" = None  # (gen, reason)
        # structured per-node preflight refusals ({"node","kind","reason"})
        # from the last plan this rank computed — /healthz + status file
        self._member_refusal_nodes: "list[dict]" = []
        self._member_committed_gen: "int | None" = None  # rank-0 manifest marker
        self._member_attempts = 0  # transient-abort retries of the pending gen
        self._member_in_flight = False  # transition running (no surgical rejoin)
        self._membership_left = False  # this rank drained away (leaver)
        self._member_join_gen: "int | None" = None  # joiner: generation joined
        self._mismatch_workers: "int | None" = None  # store-vs-run worker count
        # autoscale observability (parallel/autoscaler.py): the supervisor
        # exports its controller state to the supervise dir; workers mirror it
        # into /healthz + the flight recorder so flap-locks and decisions are
        # visible from inside the cluster
        self._autoscale_state: "Dict[str, Any] | None" = None
        self._autoscale_seen_gen = -1
        self._autoscale_last_read = 0.0

    def state_of(self, node: pg.Node) -> StateTable:
        if node.id not in self._materialized:
            raise KeyError(
                f"state of node {node.id} ({node.kind}) was not materialized; "
                "the static reference analysis in _compute_materialized missed a "
                "consumer — please report"
            )
        return self.states[node.id]

    def _compute_materialized(self) -> set:
        """Node ids whose output state must be kept materialized.

        The reference arranges every collection inside DD; here a node's StateTable
        is upkept only when something reads it: cross-table column references
        (``Evaluator._resolver_for``), ``ix`` targets, checkpoint snapshots (any
        persistence), and ``iterate`` graphs (nested runners read states directly).
        Everything else flows through as deltas only.
        """
        all_ids = {n.id for n in self._nodes}
        if self._persistence is not None or self._materialize_all:
            return all_ids
        needed: set = set()
        from pathway_tpu.internals.expression import ColumnExpression

        def walk_value(value: Any, input_tables: list) -> None:
            if isinstance(value, ColumnExpression):
                for ref in value._column_refs:
                    if all(ref.table is not t for t in input_tables):
                        needed.add(ref.table._node.id)
            elif isinstance(value, dict):
                for v in value.values():
                    walk_value(v, input_tables)
            elif isinstance(value, (list, tuple)):
                for v in value:
                    walk_value(v, input_tables)

        def has_cross_ref(node: pg.Node) -> bool:
            found = [False]

            def walk(value: Any) -> None:
                if found[0]:
                    return
                if isinstance(value, ColumnExpression):
                    for ref in value._column_refs:
                        if all(ref.table is not t for t in node.inputs):
                            found[0] = True
                            return
                elif isinstance(value, dict):
                    for v in value.values():
                        walk(v)
                elif isinstance(value, (list, tuple)):
                    for v in value:
                        walk(v)

            walk(node.config)
            return found[0]

        for node in self._nodes:
            if isinstance(node, (pg.IterateNode, pg.IterateResultNode)):
                return all_ids
            input_tables = list(node.inputs)
            walk_value(node.config, input_tables)
            if isinstance(node, pg.RowwiseNode) and has_cross_ref(node):
                # cross-table refs make this a LIVE dependency: the evaluator
                # re-derives affected rows from its input's state and suppresses
                # no-ops against its own output state — both must materialize
                # (checked per node: the referenced table may already be in
                # `needed` from another consumer)
                needed.add(node.inputs[0]._node.id)
                needed.add(node.id)
            if isinstance(node, pg.IxNode) and len(node.inputs) > 1:
                needed.add(node.inputs[1]._node.id)
        return needed & all_ids

    def current_delta_of(self, node: pg.Node) -> Optional[Delta]:
        """The delta ``node`` emitted in the current substep (None before it ran).
        Lets evaluators resolve retraction rows against retracted upstream values."""
        return self._substep_deltas.get(node.id)

    # The cluster blocklist is EMPTY: every operator kind runs multi-process.
    # Kinds either exchange (rowkey/custom routing), centralize on process 0
    # (sort, time behaviors, and — since r5 — iterate's nested fixpoint and
    # row transformers' pointer-chasing context, which recompute from full
    # state that cannot be co-partitioned), or replicate (ix/external_index
    # broadcast their lookup side) — see ``Evaluator.CLUSTER_POLICIES``.
    _CLUSTER_UNSUPPORTED: set = set()

    def setup(self, monitoring_level: Any = None, persistence_config: Any = None) -> None:
        # hot-path modules load now, not inside the first timed commit
        from pathway_tpu.engine import index as _index  # noqa: F401
        from pathway_tpu.ops import segment as _segment  # noqa: F401
        from pathway_tpu.engine.evaluators import EVALUATORS
        from pathway_tpu.internals.chaos import get_chaos
        from pathway_tpu.internals.config import get_pathway_config as _get_cfg
        from pathway_tpu.parallel.cluster import get_cluster

        self._cluster = None if self._materialize_all else get_cluster()
        self._chaos = None if self._materialize_all else get_chaos()
        self._rank = _get_cfg().process_id
        import os as _os

        self._supervise_dir = None if self._materialize_all else _os.environ.get(
            "PATHWAY_SUPERVISE_DIR"
        )
        self._surgical = (
            not self._materialize_all
            and _os.environ.get("PATHWAY_RESTART_MODE") == "surgical"
        )
        if not self._materialize_all:
            # nested iterate runners share the outer commit's clock; profiling
            # them would double-count their wall time under the outer operator
            from pathway_tpu.engine import profile as _profile

            if _profile.profiling_enabled():
                self._profiler = _profile.get_profiler()
            self._recorder = _profile.get_flight_recorder()
            self._recorder.configure(
                rank=self._rank, default_dir=self._supervise_dir
            )
            # the tracing plane shares the recorder's rank/dump-dir config so
            # trace-rank-N.jsonl lands beside flight-rank-N.json
            _tracing.get_tracer().configure(
                rank=self._rank, default_dir=self._supervise_dir
            )
        if self._cluster is not None:
            bad = sorted(
                {n.kind for n in self.graph.nodes if n.kind in self._CLUSTER_UNSUPPORTED}
            )
            if bad:
                raise NotImplementedError(
                    f"operators {bad} keep per-key state that is not co-partitioned "
                    "across spawn processes; run this pipeline single-process "
                    "(spawn -n 1) or restructure around groupby/join"
                )
            from pathway_tpu.internals.expression import ColumnExpression

            def refs_in(node: pg.Node, value: Any) -> list:
                found: list = []

                def walk(v: Any) -> None:
                    if isinstance(v, ColumnExpression):
                        for ref in v._column_refs:
                            if all(ref.table is not t for t in node.inputs):
                                found.append(ref.table)
                    elif isinstance(v, dict):
                        for x in v.values():
                            walk(x)
                    elif isinstance(v, (list, tuple)):
                        for x in v:
                            walk(x)

                walk(value)
                return found

            # PLACEMENT analysis: which process holds each node's rows. Cross-
            # table references resolve against locally materialized state, so a
            # reference is legal exactly when both sides are co-located:
            #   ("own",)     — rows live at shard_of(row_key): outputs of
            #                  row-key / group-key exchanges through
            #                  key-preserving chains (two such tables with the
            #                  same universe are co-located by construction)
            #   ("ingest",)  — never exchanged: rows sit where they entered
            #   ("root",)    — centralized on process 0
            #   ("at", id)   — produced at exchange/key-derivation point `id`
            #   ("mixed",id) — inputs disagree; matches nothing but itself
            from pathway_tpu.engine.evaluators import EVALUATORS, Evaluator

            _dummy_cache: dict = {}

            def class_policies(node: pg.Node) -> tuple:
                cls = EVALUATORS.get(type(node))
                if cls is None:
                    return tuple(None for _ in node.inputs)
                import types as _types

                dummy = _dummy_cache.get(cls)
                if dummy is None:
                    dummy = _types.SimpleNamespace(CLUSTER_POLICIES=cls.CLUSTER_POLICIES)
                    _dummy_cache[cls] = dummy
                out = []
                for i in range(len(node.inputs)):
                    try:
                        out.append(cls.cluster_input_policy(dummy, i))
                    except Exception:
                        out.append("custom")  # stateful override: assume it routes
                return tuple(out)

            _KEY_PRESERVING = {
                "rowwise", "filter", "update_rows", "update_cells", "intersect",
                "difference", "restrict", "having", "with_universe_of",
                "remove_errors", "concat", "output", "asof_now_update",
            }
            _placement_cache: dict = {}

            def placement(node: pg.Node) -> tuple:
                got = _placement_cache.get(node.id)
                if got is not None:
                    return got
                if isinstance(node, pg.InputNode):
                    p: tuple = ("ingest",)
                else:
                    pol = class_policies(node)
                    if "root" in pol:
                        p = ("root",)
                    elif node.kind == "groupby":
                        # routed by group key == output row key
                        p = ("own",)
                    elif node.kind == "join" or "custom" in pol:
                        # exchanged by a non-output key (join key, instance):
                        # rows land at that key's owner, a place all its own
                        p = ("at", node.id)
                    elif "rowkey" in pol:
                        p = ("own",)
                    else:
                        contrib = [
                            placement(inp._node)
                            for i, inp in enumerate(node.inputs)
                            if pol[i] != "broadcast"
                        ] or [placement(inp._node) for inp in node.inputs]
                        if not contrib:
                            p = ("ingest",)
                        elif all(c == contrib[0] for c in contrib):
                            p = contrib[0]
                        else:
                            p = ("mixed", node.id)
                        if p == ("own",) and node.kind not in _KEY_PRESERVING:
                            # key-changing op over key-owned rows: rows stay put
                            # but no longer sit at their (new) key's owner
                            p = ("at", node.id)
                _placement_cache[node.id] = p
                return p

            # nested-graph kinds hold inner-table expressions in their config;
            # the whole nested graph runs where the evaluator runs (root), so
            # those are not cross-process references
            _NESTED_KINDS = {"iterate", "iterate_result", "row_transformer", "row_transformer_result"}
            for node in self.graph.nodes:
                if node.kind in _NESTED_KINDS:
                    continue
                if node.kind == "groupby":
                    # the two expression sites evaluate in DIFFERENT frames:
                    # grouping expressions run PRE-exchange (rows still at the
                    # input's placement), reducer args run POST-exchange (rows
                    # at the group key's owner, where no foreign table's shard
                    # can be assumed present)
                    config_no_grouping = {
                        k: v for k, v in node.config.items() if k != "grouping"
                    }
                    if refs_in(node, config_no_grouping):
                        raise NotImplementedError(
                            f"node {node.id} (groupby) reducer arguments reference "
                            "another table's state, which is evaluated after the "
                            "group-key exchange where that state is not resident — "
                            "inline the referenced columns before the groupby "
                            "(select/join them onto the input) or run single-process"
                        )
                    refs = refs_in(node, node.config.get("grouping"))
                    own = placement(node.inputs[0]._node)
                else:
                    refs = refs_in(node, node.config)
                    own = placement(node)
                for ref_table in refs:
                    if placement(ref_table._node) != own:
                        raise NotImplementedError(
                            f"node {node.id} ({node.kind}) cross-references table "
                            f"{ref_table._node.id}, whose rows are partitioned "
                            f"differently across spawn processes "
                            f"({placement(ref_table._node)} vs {own}); the "
                            "referenced state cannot be resolved remotely — "
                            "inline the referenced columns before the exchange "
                            "(select/join them onto the input) or run "
                            "single-process"
                        )

        self._nodes = list(self.graph.nodes)
        for node in self._nodes:
            if node.id in self.evaluators:
                continue
            evaluator_cls = EVALUATORS.get(type(node))
            if evaluator_cls is None:
                raise NotImplementedError(f"no evaluator for node kind {node.kind!r}")
            self.evaluators[node.id] = evaluator_cls(node, self)
            columns = node.output.column_names() if node.output is not None else []
            self.states[node.id] = StateTable(columns)
        shared_threads = self._bind_cluster_policies()
        self._sources = [
            (node, self.evaluators[node.id])
            for node in self._nodes
            if isinstance(node, pg.InputNode)
        ]
        self._shared_nonroot = shared_threads and self._cluster.me != 0
        if self._shared_nonroot:
            # transparent-threads mode, rank > 0: the ONE shared set of source
            # objects is polled by rank 0 alone (rows reach this rank through
            # the key exchange); touching them here would double-ingest
            self._sources = []
        replay_frames = []
        ckpt_floor = 0
        if persistence_config is not None and persistence_config.backend is not None:
            from pathway_tpu.persistence.engine import PersistenceManager

            self._persistence = PersistenceManager(persistence_config)
            # "silent_replay" keeps external sinks from re-receiving already-delivered
            # rows on resume (in-process subscribers then rebuild state themselves)
            self.replay_outputs = persistence_config.persistence_mode != "silent_replay"
            sig = self.graph.sig()
            self._graph_sig = sig
            self._snapshot_interval_s = (
                getattr(persistence_config, "snapshot_interval_ms", 0) or 0
            ) / 1000.0
            if self._cluster is not None:
                if self._persistence.load_checkpoint(sig) is not None:
                    # an UNVERSIONED per-shard snapshot can only come from a
                    # single-process run whose journal was compacted at an
                    # unsynchronized commit; resuming it under spawn would
                    # silently double-count exchanged rows. (Worker-count
                    # mismatches are also refused by the store-wide meta.)
                    raise NotImplementedError(
                        "this persistence store contains an operator snapshot "
                        "(written by a single-process run); resuming it under "
                        "spawn -n N is not supported — restart single-process or "
                        "start the cluster from a fresh store"
                    )
                # coordinated cluster checkpoints: cadence from
                # PATHWAY_CHECKPOINT_INTERVAL_S (fallback: the config's
                # snapshot interval); the checkpoint marker rides the
                # per-commit neu allgather so all ranks snapshot at ONE commit
                from pathway_tpu.internals.config import env_float as _env_float

                if self._persistence.supports_cluster_checkpoints:
                    self._ckpt_interval_s = max(
                        0.0,
                        _env_float(
                            "PATHWAY_CHECKPOINT_INTERVAL_S", self._snapshot_interval_s
                        ),
                    )
                self._ckpt_compact = (
                    _os.environ.get("PATHWAY_CHECKPOINT_COMPACT", "1") != "0"
                )
                self._snapshot_interval_s = 0.0  # the single-process path stays off
                checkpoint = None
                joiner = _os.environ.get("PATHWAY_MEMBERSHIP_JOIN") == "1"
                if joiner:
                    # a grow-transition joiner: its catch-up basis is the
                    # membership manifest + handoff fragments + journal tail
                    # (never a full-history replay) — wait for the members to
                    # commit it
                    self._membership_state = "joining"
                    self._target_workers = self._cluster.n
                    manifest = self._await_membership_manifest(sig)
                else:
                    manifest = self._persistence.load_cluster_manifest(sig)
                # (a joiner's manifest comes from _await_membership_manifest,
                # which returns only membership manifests or raises typed —
                # the never-committed case is reported there)
                if manifest is not None:
                    base = int(manifest["commit_id"])
                    self._manifest_commit = base
                    membership = manifest.get("membership")
                    if joiner:
                        self._member_join_info = membership
                    if membership:
                        # membership manifest: the per-rank "snapshot" is the
                        # set of handoff fragments addressed to this rank
                        frags = self._persistence.load_reshard_fragments(
                            sig, base, self._rank, int(membership["from_n"])
                        )
                        checkpoint = (base, ("fragments", frags, membership))
                    else:
                        checkpoint = (
                            base,
                            self._persistence.load_cluster_snapshot(sig, base),
                        )
                    ckpt_floor = base + 1
            else:
                checkpoint = self._persistence.load_checkpoint(sig)
            if (
                self._surgical
                and self._cluster is not None
                and getattr(self._cluster, "supports_rejoin", False)
            ):
                # incremental rewind (fence rung 1): keep per-commit undo
                # records + the mesh serve log so a fenced survivor undoes only
                # the interrupted commit. Drain-sensitive operators emit on a
                # live-only signal replay cannot reproduce, so graphs holding
                # them skip the rewind rung (rung 2 stays exact).
                self._undo_depth = getattr(self._cluster, "commit_log_depth", 0)
                self._undo_max_bytes = int(
                    _env_float("PATHWAY_UNDO_MAX_STATE_BYTES", 64 * 1024 * 1024)
                )
                self._rewind_safe = all(
                    getattr(ev, "REWIND_SAFE", True)
                    for ev in self.evaluators.values()
                )
            replay_frames = self._persistence.load_journal(sig)
            self._persistence.open_for_append(sig)
            restore_frames = list(replay_frames)
            if checkpoint is not None:
                base_commit, blob = checkpoint
                if isinstance(blob, tuple) and blob[0] == "fragments":
                    # membership-manifest restore: merge the handoff
                    # fragments addressed to this rank (they are complete,
                    # disjoint partitions — together they ARE this rank's
                    # snapshot at the transition commit)
                    from pathway_tpu.parallel.membership import (
                        import_fragments,
                        merge_fragment_sources,
                    )

                    _frags = blob[1]
                    import_fragments(self, _frags)
                    self._deliver_sink_snapshots()
                    src_offsets, src_deltas = merge_fragment_sources(_frags)
                    park = self._persistence.load_source_park(sig)
                    if park:
                        # a drained leaver's rank-local source continuation:
                        # this joiner reuses its rank id and must not
                        # re-ingest what the old incarnation contributed
                        for nid, offs in park.get("offsets", {}).items():
                            src_offsets.setdefault(int(nid), {}).update(offs)
                    blob_sources = {
                        "source_offsets": src_offsets,
                        "source_deltas": src_deltas,
                    }
                else:
                    self._load_checkpoint_state(blob)
                    blob_sources = {
                        "source_offsets": blob["source_offsets"],
                        "source_deltas": blob["source_deltas"],
                    }
                self._commit = base_commit + 1
                # frames ≤ the checkpointed commit are subsumed by it (compaction may
                # have crashed before truncating the journal)
                replay_frames = [f for f in replay_frames if f[0] > base_commit]
                if self._cluster is not None:
                    import logging

                    # the bounded-recovery claim made observable: a replacement
                    # rank names its base manifest + the tail it still replays
                    logging.getLogger("pathway_tpu").warning(
                        "rank %d: cold-starting from %s "
                        "at commit %d (+%d journal tail frame(s))",
                        self._rank,
                        "membership manifest + handoff fragments"
                        if isinstance(blob, tuple)
                        else "cluster checkpoint manifest",
                        base_commit, len(replay_frames),
                    )
                synthetic = (
                    base_commit,
                    {},
                    {
                        nid: {
                            **blob_sources["source_offsets"].get(nid, {}),
                            **(
                                {"state_deltas": blob_sources["source_deltas"][nid]}
                                if blob_sources["source_deltas"].get(nid)
                                else {}
                            ),
                        }
                        for nid in set(blob_sources["source_offsets"])
                        | set(blob_sources["source_deltas"])
                    },
                )
                restore_frames = [synthetic, *replay_frames]
            if restore_frames:
                self._restore_sources(restore_frames)
        self._materialized = self._compute_materialized()
        self._build_fusion()
        for node, evaluator in self._sources:
            node.config["source"].on_start()
        self._monitor = _make_monitor(monitoring_level, self._nodes)
        self._ready = True
        # replay journaled input deltas through the (deterministic) graph to rebuild
        # every operator's state, before any realtime stepping
        from pathway_tpu.internals.config import get_pathway_config

        if self._cluster is not None and self._persistence is not None:
            join_info = getattr(self, "_member_join_info", None)
            if join_info is not None:
                # joiner: no replay (the fragments ARE the state at the
                # transition commit) — synchronize with the members' install
                # barrier and enter the lockstep loop at commit C+1
                gen = int(join_info.get("generation", 0))
                self._cluster.allgather(f"member:install:{gen}".encode(), None)
                self._membership_state = "stable"
                self._member_done_gen = gen
                self._target_workers = self._cluster.n
                import logging

                logging.getLogger("pathway_tpu").warning(
                    "rank %d: joined the cluster at epoch %d (n=%d, "
                    "generation %d) from the membership manifest — no "
                    "journal replay",
                    self._rank, getattr(self._cluster, "epoch", 0),
                    self._cluster.n, gen,
                )
            else:
                self._cluster_replay(replay_frames, floor=ckpt_floor)
        else:
            if replay_frames and get_pathway_config().persistence_mode == "batch":
                # replay the whole recording as ONE commit (reference PersistenceMode::Batch)
                merged: Dict[int, List[Delta]] = {}
                for _cid, input_deltas, _offs in replay_frames:
                    for nid, delta in input_deltas.items():
                        merged.setdefault(nid, []).append(delta)
                combined = {
                    nid: Delta.concat(ds, list(ds[0].columns)) for nid, ds in merged.items()
                }
                replay_frames = [(replay_frames[-1][0], combined, replay_frames[-1][2])]
            for commit_id, input_deltas, _offsets in replay_frames:
                self._inject = input_deltas
                self.step()
            self._inject = None
            if replay_frames:
                # future frame ids must exceed every journaled id (checkpoint subsumption
                # filters by id)
                self._commit = max(self._commit, replay_frames[-1][0] + 1)

    def _analysis_context(self, *, persistence: "bool | None" = None) -> Any:
        """The ONE AnalysisContext of this runner (DAG walk + consumer maps +
        dtype propagation), built lazily and shared by the lint gate and the
        fusion planner — a regression test asserts a single construction per
        ``pw.run``."""
        if self._analysis_ctx is None:
            from pathway_tpu.analysis import AnalysisContext

            if persistence is None:
                persistence = self._persistence is not None
            self._analysis_ctx = AnalysisContext(self.graph, persistence=persistence)
        return self._analysis_ctx

    def _fusion_mode(self) -> str:
        mode = os.environ.get("PATHWAY_FUSION", "on").strip().lower()
        if mode in ("off", "0", "false", "no", "none"):
            return "off"
        if mode not in ("on", "1", "true", "yes", ""):
            import logging

            # a typo (PATHWAY_FUSION=fast) must not silently flip the default
            logging.getLogger("pathway_tpu").warning(
                "unrecognized PATHWAY_FUSION=%r (expected off|on); keeping the "
                "default 'on'",
                mode,
            )
        return "on"

    def _build_fusion(self) -> None:
        """Plan whole-commit fusion and compile the substep schedule
        (``PATHWAY_FUSION=off`` or a plan with no chains leaves the stock
        per-node dispatch untouched). Runs inside ``setup`` after evaluators
        and the materialization set exist — journal replay already executes
        fused."""
        self._fusion_schedule = None
        if self._materialize_all or self._fusion_mode() == "off":
            # nested iterate runners share the outer commit's substep; fusing
            # them would double-attribute and complicate the inner fixpoint
            return
        from pathway_tpu.analysis.fusion import plan_fusion
        from pathway_tpu.engine.fusion import build_schedule

        plan = plan_fusion(self._analysis_context())
        self._fusion_schedule = build_schedule(self, plan)
        if self._fusion_schedule is not None and self._recorder is not None:
            # the region plan rides the flight recorder so a post-mortem dump
            # names what was fused at crash time
            self._recorder.record_event("fusion", **plan.to_event())

    def _bind_cluster_policies(self) -> bool:
        """Stamp every evaluator with its per-input cluster routing policies and
        barrier participation (re-run after a surgical-rejoin state reset — the
        fresh evaluators need the same stamps the originals got in setup).
        Returns the transparent-threads flag."""
        shared_threads = self._cluster is not None and getattr(
            self._cluster, "shared_inputs", False
        )
        if self._cluster is not None:
            for node in self._nodes:
                ev = self.evaluators[node.id]
                ev._cluster_policies = tuple(
                    ev.cluster_input_policy(i) for i in range(len(node.inputs))
                )
                # exchange/centralize/broadcast points are lockstep barriers:
                # they participate in every commit even with no local rows
                ev._cluster_barrier = node.kind in ("groupby", "join") or any(
                    p is not None for p in ev._cluster_policies
                )
                if shared_threads and isinstance(node, pg.OutputNode):
                    # transparent-threads mode: sinks live on rank 0 only, so
                    # every worker ships its output partition to the root —
                    # callbacks stay single-threaded and see ALL rows, in the
                    # same per-commit batches a 1-thread run delivers
                    ev._cluster_policies = tuple("root" for _ in node.inputs)
                    ev._cluster_barrier = True
        return shared_threads

    def _load_checkpoint_state(self, blob: dict) -> None:
        """Restore operator + state-table snapshots (reference operator persistence,
        ``dataflow/persist.rs``); live sinks then receive the restored state as one
        snapshot delivery (they cannot re-hear the compacted history)."""
        from pathway_tpu.engine.evaluators import OutputEvaluator

        for nid, sblob in blob["states"].items():
            if nid in self.states:
                self.states[nid].load_state_blob(sblob)
        for nid, estate in blob["evaluators"].items():
            evaluator = self.evaluators.get(nid)
            if evaluator is not None:
                evaluator.load_state_dict(estate)
        self._deliver_sink_snapshots()

    def _deliver_sink_snapshots(self) -> None:
        """Live sinks receive the restored/imported state as one snapshot
        delivery (they cannot re-hear compacted history; after a membership
        import this also hands a rank its newly-gained rows)."""
        from pathway_tpu.engine.evaluators import OutputEvaluator

        if not self.replay_outputs:
            return
        for node in self._nodes:
            evaluator = self.evaluators[node.id]
            if isinstance(evaluator, OutputEvaluator):
                snapshot = self.states[node.inputs[0]._node.id].snapshot()
                if len(snapshot):
                    evaluator.process([snapshot])

    def _await_membership_manifest(self, sig: str) -> dict:
        """Joiner-side wait for the members to commit the membership
        manifest (bounded by the fence timeout; a refused/aborted transition
        leaves the joiner to die typed and the supervisor cleans up).
        Worker-count mismatches against OLDER manifests are expected while
        the transition is still in flight — keep polling."""
        from pathway_tpu.internals.config import env_float as _env_float
        from pathway_tpu.parallel.cluster import PeerTimeoutError
        from pathway_tpu.parallel.membership import MembershipMismatchError

        deadline = time_mod.monotonic() + _env_float(
            "PATHWAY_MEMBERSHIP_DEADLINE_S",
            _env_float("PATHWAY_FENCE_TIMEOUT_S", 180.0),
        )
        while True:
            try:
                manifest = self._persistence.load_cluster_manifest(sig)
            except MembershipMismatchError:
                manifest = None  # pre-transition manifest still newest
            if manifest is not None and manifest.get("membership"):
                return manifest
            if time_mod.monotonic() > deadline:
                raise PeerTimeoutError(
                    f"joiner rank {self._rank}: no membership manifest "
                    "appeared within the deadline — the transition aborted "
                    "or never started"
                )
            self._publish_status(force=True)
            time_mod.sleep(0.25)

    def _snapshot_blob(self) -> "tuple[dict | None, str]":
        """Build the full engine snapshot (operator + state-table + source
        state). Returns ``(blob, "ok")``, ``(None, "defer")`` while any source
        is mid-segment (a segment's pre-checkpoint events would be baked into
        state while its tail stays in the journal, making a changed-segment
        undo impossible), or ``(None, "permanent: ...")`` for unpicklable
        operator state."""
        from pathway_tpu.engine.evaluators import (
            InputEvaluator,
            OutputEvaluator,
            UnpicklableStateError,
        )

        offsets = {
            # per-frame marker payloads don't belong in the checkpoint snapshot
            n.id: {k: v for k, v in n.config["source"].offset_state().items() if k != "state_deltas"}
            for n, _ in self._sources
        }
        if any(o.get("in_progress") for o in offsets.values()):
            return None, "defer"
        deltas = {
            n.id: n.config["source"].checkpoint_state_deltas() for n, _ in self._sources
        }
        try:
            blob = {
                "states": {nid: st.state_blob() for nid, st in self.states.items()},
                "evaluators": {
                    nid: ev.state_dict()
                    for nid, ev in self.evaluators.items()
                    if not isinstance(ev, (InputEvaluator, OutputEvaluator))
                },
                "source_offsets": offsets,
                "source_deltas": deltas,
            }
        except UnpicklableStateError as exc:
            return None, f"permanent: {exc}"
        return blob, "ok"

    def _take_checkpoint(self) -> bool:
        """Single-process checkpoint: snapshot, then compact the journal."""
        blob, why = self._snapshot_blob()
        if blob is None:
            if why.startswith("permanent"):
                if not self._warned_unpicklable:
                    self._warned_unpicklable = True
                    import logging

                    logging.getLogger("pathway_tpu").warning(
                        "operator checkpointing disabled: %s — falling back to "
                        "full journal replay on resume",
                        why,
                    )
                self._snapshot_interval_s = 0.0  # stop retrying every commit
            return False
        self._persistence.dump_checkpoint(self._graph_sig, self._commit, blob)
        return True

    def _coordinated_checkpoint(self) -> None:
        """Cluster-coordinated checkpoint at ONE lockstep commit id (the
        decision rode this commit's neu allgather, so every rank is here).

        Barrier sequence: (1) every rank writes its versioned snapshot; (2)
        durability acks are allgathered — any non-ok rank aborts the attempt
        cluster-wide and the previous checkpoint stands; (3) rank 0 commits the
        manifest (read-back verified) and the outcome is allgathered; (4) only
        then does every rank compact its journal shard and prune old
        snapshots/manifests + the mesh serve log. A crash at ANY point leaves
        the previous checkpoint + uncompacted journal recoverable
        (chaos-tested: post-snapshot kill, torn manifest, snapshot error)."""
        from pathway_tpu.engine import telemetry
        from pathway_tpu.engine.profile import histogram

        cluster = self._cluster
        t0 = time_mod.monotonic()
        epoch = getattr(cluster, "epoch", 0)
        if self._chaos is not None:
            self._chaos.begin_checkpoint_attempt()
            # plain rank death scheduled after N completed checkpoints (the
            # acceptance headline) — before anything of THIS attempt is written
            self._chaos.maybe_checkpoint_kill(
                self._rank, self._commit, epoch=epoch, op="pre_snapshot_kill"
            )
        blob, status = self._snapshot_blob()
        size = 0
        if blob is not None:
            try:
                size = self._persistence.dump_cluster_snapshot(
                    self._graph_sig, self._commit, blob
                )
            except (ConnectionError, OSError) as exc:
                status = f"transient: {exc}"
        if self._chaos is not None:
            # fault window: this rank's snapshot is durable, the manifest is not
            self._chaos.maybe_checkpoint_kill(self._rank, self._commit, epoch=epoch)
        statuses = cluster.allgather(f"ckptack:{self._commit}".encode(), status)
        if any(s.startswith("permanent") for s in statuses):
            self._ckpt_disabled_reason = next(
                s for s in statuses if s.startswith("permanent")
            )
            self._ckpt_interval_s = 0.0
            import logging

            logging.getLogger("pathway_tpu").warning(
                "coordinated checkpoints disabled cluster-wide (%s) — rejoin "
                "falls back to full journal replay",
                self._ckpt_disabled_reason,
            )
            return
        if any(s != "ok" for s in statuses):
            # transient backend error or a mid-segment defer somewhere: no
            # manifest, previous checkpoint stands, retry at the next commit
            telemetry.stage_add("persist.checkpoint_retries")
            if self._recorder is not None:
                self._recorder.record_event(
                    "checkpoint_deferred", commit=self._commit,
                    statuses=[s.split(":")[0] for s in statuses],
                )
            return
        ok = True
        if self._rank == 0:
            ok = self._persistence.commit_cluster_manifest(
                self._graph_sig, self._commit, epoch=epoch
            )
        oks = cluster.allgather(f"ckptdone:{self._commit}".encode(), bool(ok))
        if not all(oks):
            # torn/failed manifest: every rank keeps its journal intact; the
            # orphan snapshots are pruned by the next successful checkpoint
            telemetry.stage_add("persist.checkpoint_manifest_failures")
            return
        tail_frames = 0
        if self._ckpt_compact:
            tail_frames = self._persistence.compact_journal(self._graph_sig)
        self._persistence.cleanup_cluster_checkpoints(self._commit)
        # a parked leaver source continuation (restored if this rank rejoined
        # after a scale-down) is superseded once a durable snapshot carries
        # the live offsets
        self._persistence.clear_source_park()
        cluster.prune_commit_log(self._commit)
        self._manifest_commit = self._commit
        self._last_checkpoint = time_mod.monotonic()
        duration = self._last_checkpoint - t0
        # recovery-SLO instrumentation (PR 5 metrics plane): checkpoint
        # cadence/size/duration and the journal-tail length it compacted away
        histogram("pathway_checkpoint_duration_seconds").observe(duration)
        telemetry.stage_add_many({
            "persist.checkpoints": 1.0,
            "persist.checkpoint_bytes": float(size),
            "persist.checkpoint_s": duration,
            "persist.journal_frames_compacted": float(tail_frames),
        })
        if self._recorder is not None:
            self._recorder.record_event(
                "checkpoint",
                commit=self._commit,
                epoch=epoch,
                bytes=size,
                duration_s=round(duration, 4),
                journal_frames_compacted=tail_frames,
            )

    def _restore_sources(self, frames: List[tuple]) -> None:
        """Fold journaled segment-state deltas and the unmarked tail back into each
        source (reference ``Connector::read_snapshot`` + ``OffsetValue`` seek)."""
        from pathway_tpu.internals.keys import keys_to_pointers

        last_offsets = frames[-1][2]
        for node, _ in self._sources:
            nid = node.id
            offsets = last_offsets.get(nid, {})
            rehydrate = getattr(
                getattr(node.config["source"], "subject", None),
                "rehydrate_state_deltas",
                None,
            )
            # journal-frame markers are slim (no row payload): re-derive
            # each marker's rows from the input deltas journaled up to its
            # frame (row keys are content-addressed, the lookup is exact).
            # Checkpoint/fragment deltas arrive hydrated and pass through.
            row_values: Dict[bytes, Any] = {}
            fed_until = 0

            def _feed_rows(up_to: int) -> None:
                nonlocal fed_until
                for f_idx in range(fed_until, up_to):
                    delta = frames[f_idx][1].get(nid)
                    if delta is None or len(delta) == 0:
                        continue
                    for i in range(len(delta)):
                        if delta.diffs[i] > 0:
                            row_values[delta.keys[i].tobytes()] = {
                                n: c[i] for n, c in delta.columns.items()
                            }
                fed_until = max(fed_until, up_to)

            state_deltas: List[Any] = []
            last_marker_idx = -1
            for idx, (_cid, _deltas, offs) in enumerate(frames):
                deltas = offs.get(nid, {}).get("state_deltas")
                if deltas:
                    if rehydrate is not None and any(
                        "rows" not in d and not d.get("deleted") for d in deltas
                    ):
                        _feed_rows(idx + 1)
                        deltas = rehydrate(deltas, row_values)
                    state_deltas.extend(deltas)
                    last_marker_idx = idx
            tail: Optional[dict] = None
            if offsets.get("consumed", 0) > 0 or offsets.get("done"):
                tail_rows: List[tuple] = []
                for _cid, input_deltas, _offs in frames[last_marker_idx + 1 :]:
                    delta = input_deltas.get(nid)
                    if delta is None or len(delta) == 0:
                        continue
                    pointers = keys_to_pointers(delta.keys)
                    for i in range(len(delta)):
                        values = {n: c[i] for n, c in delta.columns.items()}
                        tail_rows.append((pointers[i], values, int(delta.diffs[i])))
                in_progress = offsets.get("in_progress") or {}
                covered = 0
                if last_marker_idx >= 0:
                    covered = frames[last_marker_idx][2].get(nid, {}).get("consumed", 0)
                tail = {
                    "token": in_progress.get("token"),
                    "fp": in_progress.get("fp"),
                    "count": in_progress.get("emitted", 0),
                    "rows": tail_rows,
                    # events up to `covered` are accounted for by segment markers; only
                    # a marker-less subject re-pushes its whole history
                    "covered": covered,
                    "has_markers": last_marker_idx >= 0,
                }
            node.config["source"].restore(offsets, state_deltas, tail)

    def _cluster_replay(self, replay_frames: List[tuple], floor: int = 0) -> None:
        """Lockstep journal replay across the cluster: journals differ after a
        mid-commit kill (one process recorded commit N, its peer died first),
        and a commit with data on only one process writes a frame only there.
        Exchange tags carry the commit id, so every process must replay the
        UNION of recorded ids at their ORIGINAL numbering — injecting an empty
        frame where it has no local data — or the all-to-all deadlocks.
        (Reference: timely workers replay a shared total order of timestamps.)
        Runs at initial setup AND after a surgical-rejoin state reset; either
        way every rank leaves with the same ``_commit`` counter, so post-replay
        barrier tags line up. ``floor`` is the post-replay commit counter when
        nothing is journaled (manifest commit + 1 under a cluster checkpoint —
        every rank computes the same floor from the same manifest)."""
        local_frames = {cid: deltas for cid, deltas, _offs in replay_frames}
        all_ids = self._cluster_replay_ids(local_frames)
        # rung-coordination barrier (see _attempt_surgical_rejoin): a fresh or
        # replacement rank has no retained state, so it votes "no interrupted
        # commit" and always step-replays — the vote only keeps its barrier
        # tag sequence aligned with fenced survivors deciding serve-vs-step
        self._cluster.allgather(b"replay:mode", None)
        self._cluster_replay_steps(local_frames, all_ids, floor)

    def _cluster_replay_ids(self, local_frames: Dict[int, Any]) -> List[int]:
        """The union of journaled commit ids across the cluster (one allgather;
        both the step-replay and the serve-from-log rejoin paths start here, so
        a rank may decide its mode AFTER learning the union without skewing the
        barrier tag sequence)."""
        id_lists = self._cluster.allgather(b"replay:ids", sorted(local_frames))
        return sorted(set().union(*id_lists))

    def _cluster_replay_steps(
        self, local_frames: Dict[int, Any], all_ids: List[int], floor: int = 0
    ) -> None:
        from pathway_tpu.internals.config import get_pathway_config

        if all_ids and get_pathway_config().persistence_mode == "batch":
            # batch mode, cluster flavor: collapse every local frame into ONE
            # replay commit pinned at the globally-last journaled id, so the
            # single replayed commit carries the same exchange tags everywhere
            merged: Dict[int, List[Delta]] = {}
            for deltas in local_frames.values():
                for nid, delta in deltas.items():
                    merged.setdefault(nid, []).append(delta)
            combined = {
                nid: Delta.concat(ds, list(ds[0].columns))
                for nid, ds in merged.items()
            }
            local_frames = {all_ids[-1]: combined}
            all_ids = [all_ids[-1]]
        for cid in all_ids:
            self._commit = cid
            self._inject = local_frames.get(cid, {})
            self.step()
        self._inject = None
        # nothing journaled anywhere: every rank aligns at the floor (0 on a
        # fresh store; manifest commit + 1 under a cluster checkpoint — a
        # fenced survivor may arrive here mid-commit-N; leaving its counter
        # ahead of the replacement's would skew every post-rejoin barrier tag)
        self._commit = all_ids[-1] + 1 if all_ids else floor

    def step(self) -> bool:
        """Run one commit; returns True if any node produced output.

        Each commit runs in two phases mirroring the reference's alt/neu timestamps
        (``dataflow.rs:3447``): the even ("alt") phase moves normal data; the odd ("neu")
        phase moves *forgetting* retractions drained from Forget/AsofNow operators. Keeping
        the phases separate guarantees a delta is never a mix of real updates and
        forgetting updates, so ``_filter_out_results_of_forgetting`` can drop whole neu
        deltas without losing genuine data.

        The commit is the root of the commit-plane trace: its trace id is a
        pure function of ``(epoch, commit)``, so every rank's commit span is a
        sibling in ONE trace without anything riding the wire, and barrier /
        checkpoint spans opened below become its children via the
        context-local parent. Queries admitted since the previous commit link
        in (a query racing the boundary links the adjacent commit). Operator
        child spans are synthesized AFTER the commit closes, and only for
        sampled/promoted commits — nothing on the operator hot path.
        """
        tracer = _tracing.get_tracer()
        if not tracer.enabled or self._materialize_all:
            return self._step_inner()
        epoch = (
            getattr(self._cluster, "epoch", 0) if self._cluster is not None else 0
        )
        tracer.set_epoch(epoch)
        commit = self._commit
        ctx = _tracing.commit_trace_context(epoch, commit, self._rank)
        links = tuple(tracer.take_commit_links())
        with tracer.trace_span(
            "commit",
            f"commit {commit}",
            self_ctx=ctx,
            links=links,
            attrs={"commit": commit, "epoch": epoch},
        ) as span:
            any_output = self._step_inner()
        if span is not None and span.sampled:
            self._trace_commit_ops(tracer, span)
        return any_output

    def _trace_commit_ops(self, tracer: Any, span: Any) -> None:
        """Lift the commit profile's per-evaluator rows into child spans of
        the (sampled or slow-promoted) commit span. Start offsets partition
        the commit window cumulatively — durations are what the critical-path
        walk consumes; only the slowest rows survive the cap."""
        commit_profile = self._last_commit_profile
        self._last_commit_profile = None
        if commit_profile is None or not commit_profile.ops:
            return
        ops = commit_profile.ops
        if len(ops) > 48:
            ops = sorted(ops, key=lambda op: op[3], reverse=True)[:48]
        parent = span.context()
        offset = 0.0
        for node_id, name, kind, seconds, rows, retractions, neu in ops:
            span_kind = "fused_region" if kind == "fused_chain" else "operator"
            tracer.record_span(
                span_kind,
                name,
                parent=parent,
                ts=span.ts + offset,
                ts_mono=span.ts_mono + offset,
                duration_s=seconds,
                attrs={
                    "node": node_id,
                    "op_kind": kind,
                    "rows": rows,
                    "retractions": retractions,
                    "neu": neu,
                },
            )
            offset += seconds

    def _step_inner(self) -> bool:
        commit_t0 = time_mod.monotonic()
        if self._inject is None:
            # fresh drain: these deltas belong to THIS commit (the surgical
            # fence must only carry over input rows of the interrupted commit,
            # never re-ingest an earlier, already-journaled batch)
            self._input_deltas = {}
            self._input_deltas_commit = self._commit
        if self._chaos is not None and self._inject is None:
            # fault injection: a scheduled kill fires at a LIVE commit
            # boundary — the previous commit is fully journaled, this one is
            # mid-flight everywhere else in the cluster (peers block in its
            # barriers). Journal replay (restart-all resume or a fenced
            # survivor's rollback) must never re-fire a kill, or the schedule
            # would loop forever.
            self._chaos.maybe_kill(
                self._rank,
                self._commit,
                epoch=getattr(self._cluster, "epoch", 0)
                if self._cluster is not None
                else 0,
            )
        self.current_time = self._commit * 2  # even data times, as in the reference
        self.draining = self._ready and self.sources_finished()
        undo_armed = (
            self._undo_depth > 0
            and self._rewind_safe
            and self._inject is None
            and self._cluster is not None
        )
        if undo_armed:
            # incremental rewind bookkeeping for THIS commit: the undo record
            # (inverted on a fence) and the mesh serve-log entry (served to a
            # replacement's tail replay). Both are discarded if the commit
            # completes/fails respectively — see _undo_interrupted_commit.
            self._undo_current = {
                "commit": self._commit, "applied": [], "evals": {}, "bytes": 0,
                "capture_s": 0.0,
            }
            self._cluster.begin_commit_log(self._commit)
        ckpt_due = False
        any_output = self._substep(neu=False)
        neu = any(
            getattr(self.evaluators[n.id], "neu_pending", _no_pending)()
            for n in self._nodes
        )
        if self._cluster is not None:
            # the neu phase is part of the lockstep commit protocol: every process
            # must agree whether it runs (exchange points fire inside it). The
            # coordinated-checkpoint marker RIDES this same barrier: barriers are
            # already lockstep, so every rank learns at the same commit id that a
            # checkpoint is due — aligned Chandy–Lamport for free.
            member_vote = self._membership_vote() if self._inject is None else None
            want_ckpt = (
                self._inject is None
                and self._ckpt_interval_s > 0
                and self._persistence is not None
                and time_mod.monotonic() - self._last_checkpoint
                >= self._ckpt_interval_s
                # a pending membership change writes its OWN manifest at the
                # transition commit; a racing checkpoint would be redundant
                and self._member_pending is None
            )
            votes = self._cluster.allgather(
                f"neu:{self._commit}".encode(), (neu, want_ckpt, member_vote)
            )
            neu = any(v[0] for v in votes)
            ckpt_due = any(v[1] for v in votes)
            if self._inject is None:
                self._membership_votes_seen([v[2] for v in votes])
        if neu:
            self.current_time = self._commit * 2 + 1
            any_output = self._substep(neu=True) or any_output
        if undo_armed:
            # mutations for this commit are final: seal the serve-log entry and
            # drop the undo record — a fence from here on (journaling has no
            # barriers; the checkpoint barriers come after) must NOT undo a
            # completed commit
            self._cluster.end_commit_log()
            rec_done, self._undo_current = self._undo_current, None
            if rec_done is not None and rec_done["evals"]:
                alpha = 0.2
                self._undo_capture_ewma += alpha * (
                    rec_done["capture_s"] - self._undo_capture_ewma
                )
                self._undo_commit_ewma += alpha * (
                    time_mod.monotonic() - commit_t0 - self._undo_commit_ewma
                )
                self._undo_armed_commits += 1
                if (
                    self._undo_armed_commits >= 8
                    # 1 ms absolute floor: below it the rung is cheap in wall
                    # terms and µs-level timer noise could trip the ratio
                    and self._undo_capture_ewma > 1e-3
                    and self._undo_capture_ewma > 0.25 * self._undo_commit_ewma
                ):
                    self._disable_rewind(
                        f"undo capture averages "
                        f"{self._undo_capture_ewma * 1e3:.1f} ms/commit "
                        f"({self._undo_capture_ewma / self._undo_commit_ewma:.0%} "
                        "of commit time); re-pickling this much operator state "
                        "every commit costs more than the tail replay it avoids"
                    )
        if self._persistence is not None and self._inject is None:
            offsets = {n.id: n.config["source"].offset_state() for n, _ in self._sources}
            # a frame is needed for data AND for data-less segment markers (a marker can
            # close a segment whose rows all rode earlier frames)
            if any(len(d) for d in self._input_deltas.values()) or any(
                o.get("state_deltas") for o in offsets.values()
            ):
                self._persistence.record_commit(self._commit, self._input_deltas, offsets)
                if (
                    self._snapshot_interval_s > 0
                    # single-process operator snapshots are wall-clock-driven;
                    # under a cluster the COORDINATED protocol below replaces
                    # them (an unsynchronized checkpoint would subsume commits
                    # whose exchanges a peer still needs to replay)
                    and self._cluster is None
                    and time_mod.monotonic() - self._last_checkpoint
                    >= self._snapshot_interval_s
                ):
                    with _tracing.trace_span(
                        "checkpoint", f"checkpoint {self._commit}"
                    ):
                        if self._take_checkpoint():
                            self._last_checkpoint = time_mod.monotonic()
            if ckpt_due:
                # every rank reaches this point for a due checkpoint (the
                # decision was allgathered), including ranks with no data this
                # commit — the protocol is a barrier sequence of its own
                with _tracing.trace_span(
                    "checkpoint", f"checkpoint {self._commit}"
                ):
                    self._coordinated_checkpoint()
        input_rows = sum(len(d) for d in self._input_deltas.values())
        if self.prober_stats is not None:
            self.prober_stats.record_commit(
                input_rows,
                self._output_rows_this_commit,
                self._step_counts,
                self.sources_finished(),
            )
            if self._metrics is not None:
                self._metrics.record_commit(
                    input_rows,
                    self._output_rows_this_commit,
                    time_mod.monotonic() - commit_t0,
                )
        if self._profiler is not None:
            commit_profile = CommitProfile(
                commit=self._commit,
                rank=self._rank,
                duration_s=time_mod.monotonic() - commit_t0,
                input_rows=input_rows,
                output_rows=self._output_rows_this_commit,
                neu=neu,
                ops=self._profile_ops or [],
            )
            self._profiler.record_commit(commit_profile)
            if self._recorder is not None:
                self._recorder.record_commit(commit_profile)
            self._last_commit_profile = commit_profile
            self._profile_ops = None
        if self._monitor is not None:
            self._monitor.update(self._commit, self._step_counts, self.states)
        if self._supervise_dir is not None:
            # liveness for the spawn supervisor: written from THIS loop (not a
            # helper thread) so staleness means the commit loop stopped turning
            self._publish_status()
        self._commit += 1
        if self._member_all_ready and self._inject is None:
            # every rank voted ready for the same generation at THIS commit:
            # the cluster is quiesced — run the epoch-fenced transition
            self._run_membership_transition()
        return any_output

    def _publish_status(self, force: bool = False) -> None:
        """Atomically publish this rank's liveness record for the supervisor
        (throttled; ``force`` bypasses the throttle — the fence path publishes
        on every poll so a quiesced-but-healthy survivor is never shot for
        staleness, and so operators can watch the rejoin progress)."""
        if self._supervise_dir is None:
            return
        now = time_mod.monotonic()
        if not force and now - self._last_status_write < 0.25:
            return
        from pathway_tpu.parallel.supervisor import write_status

        self._mirror_autoscale_state(now)
        health = self.health()
        write_status(
            self._supervise_dir,
            self._rank,
            commit=self._commit,
            persistence=self._persistence is not None,
            peers=health["peers"],
            epoch=health["epoch"],
            state=health["state"],
            restarts=health["restarts"],
            last_rejoin_s=health["last_rejoin_s"],
            checkpoint_commit=health["checkpoint_commit"],
            journal_tail_frames=health["journal_tail_frames"],
            extra={
                k: health[k]
                for k in (
                    "membership_state",
                    "current_workers",
                    "target_workers",
                    "membership_committed",
                    "membership_refused",
                    "membership_refusals",
                    "manifest_workers",
                    "autoscale",
                )
            },
        )
        self._last_status_write = now

    def _mirror_autoscale_state(self, now: float) -> None:
        """Mirror the supervisor's autoscale-controller state file into this
        worker's observability surfaces (throttled to ~1/s): ``/healthz``
        shows the controller state + last decision, decision changes bump
        ``autoscale.decisions``, and a flap-lock engaging lands an
        ``autoscale`` flight event — post-mortems then carry the controller's
        story next to the commit timeline."""
        if self._supervise_dir is None or now - self._autoscale_last_read < 1.0:
            return
        self._autoscale_last_read = now
        from pathway_tpu.engine import telemetry
        from pathway_tpu.parallel.autoscaler import read_state

        state = read_state(self._supervise_dir)
        if state is None:
            return
        gen = int(state.get("generation", 0) or 0)
        prev = self._autoscale_state
        self._autoscale_state = state
        if gen == self._autoscale_seen_gen:
            return
        self._autoscale_seen_gen = gen
        # the generation bumps on EVERY controller state change (issue,
        # refusal, completion, recovery re-arm) — count a DECISION only when
        # the last-decision record itself changed
        if state.get("last_decision") != (prev or {}).get("last_decision"):
            telemetry.stage_add("autoscale.decisions")
        was_locked = bool(prev and prev.get("flap_locked"))
        if state.get("flap_locked") and not was_locked:
            telemetry.stage_add("autoscale.flap_locks")
        if self._recorder is not None:
            last = state.get("last_decision") or {}
            self._recorder.record_event(
                "autoscale",
                state=state.get("state"),
                flap_locked=bool(state.get("flap_locked")),
                decision=last.get("kind"),
                target_n=last.get("target_n"),
                reason=str(last.get("reason", ""))[:160],
            )

    def _substep(self, *, neu: bool) -> bool:
        if not neu:
            self._step_counts = {}
            self._output_rows_this_commit = 0
            self._profile_ops = [] if self._profiler is not None else None
        deltas: Dict[int, Delta] = {}
        self._substep_deltas = deltas
        any_output = False
        from pathway_tpu.engine import expression_evaluator as ee_mod

        profile_ops = self._profile_ops
        runtime = ee_mod.get_runtime()
        schedule = self._fusion_schedule
        if schedule is None:
            # stock per-node dispatch (PATHWAY_FUSION=off reproduces this path
            # exactly: the schedule is never built)
            for node in self._nodes:
                if self._run_node(node, deltas, neu, profile_ops, runtime):
                    any_output = True
        else:
            for item in schedule:
                if isinstance(item, pg.Node):
                    ran = self._run_node(item, deltas, neu, profile_ops, runtime)
                else:
                    # a compiled ChainProgram covering several operators
                    ran = item.execute(self, deltas, neu, profile_ops, runtime)
                if ran:
                    any_output = True
        return any_output

    def _run_node(
        self,
        node: pg.Node,
        deltas: Dict[int, Delta],
        neu: bool,
        profile_ops: "List[tuple] | None",
        runtime: Dict[str, Any],
    ) -> bool:
        """One operator's substep turn (the pre-fusion per-node dispatch body,
        shared verbatim by the unfused loop and fused-region member nodes).
        Returns whether the node emitted rows."""
        any_output = False
        evaluator = self.evaluators[node.id]
        runtime["node"] = node
        # commit identity for UDFs that read live process-global state
        # (the /v1/statistics engine snapshot): re-derivations WITHIN one
        # commit must see the same value (a value that moved between two
        # evaluations churns nondeterministic update pairs), while the
        # next commit reads fresh — retraction rows of later commits are
        # covered by the evaluator's memoize-on-retraction, not by this.
        # Set per node because nested iterate runners share this
        # thread-local and overwrite it mid-substep.
        runtime["commit_token"] = (id(self), self._commit)
        _t_op = time_mod.perf_counter() if profile_ops is not None else 0.0
        if (
            isinstance(node, pg.OutputNode)
            and not neu
            and (self._inject is None or self.replay_outputs)
        ):
            # count only rows actually delivered to sinks (not forgetting-phase
            # retractions, not silently-replayed history)
            self._output_rows_this_commit += sum(
                len(deltas.get(inp._node.id, ())) for inp in node.inputs
            )
        if isinstance(node, pg.InputNode):
            if neu or self._shared_nonroot:
                delta = Delta.empty(self.output_columns_of(node))
            elif self._inject is not None:
                # journal replay: feed the persisted delta instead of the source
                delta = self._inject.get(
                    node.id, Delta.empty(self.output_columns_of(node))
                )
            else:
                delta = evaluator.process([])
                carry = self._rejoin_carry.pop(node.id, None)
                if carry is not None and len(carry):
                    # input rows drained by the commit a fence interrupted,
                    # never journaled: re-ingest them exactly once with the
                    # first post-rejoin batch (they journal normally now)
                    delta = (
                        Delta.concat(
                            [carry, delta], self.output_columns_of(node)
                        )
                        if len(delta)
                        else carry
                    )
            if not neu:
                self._input_deltas[node.id] = delta
            if self._cluster is not None and getattr(
                self._cluster, "shared_inputs", False
            ):
                # transparent-threads mode: scatter the freshly ingested rows
                # by row key so rowwise/filter/join work downstream runs on
                # ALL ranks, not just the ingesting rank 0 (stateful ops
                # re-exchange by their own keys as usual). Lockstep: every
                # rank reaches this exchange each commit (rank > 0 with an
                # empty delta).
                tag = f"{self.current_time}:{node.id}:scatter".encode()
                delta = self._cluster.exchange_delta(tag, delta, delta.keys)
        else:
            inputs = [
                deltas.get(inp._node.id, Delta.empty(inp.column_names()))
                for inp in node.inputs
            ]
            originates = neu and getattr(evaluator, "neu_pending", _no_pending)()
            cross_nodes = getattr(evaluator, "_cross_nodes", None)
            if (
                all(len(d) == 0 for d in inputs)
                and not originates
                and not (not neu and _has_pending(evaluator))
                and node.kind != "iterate_result"
                # a rowwise node's cross-table references are live deps:
                # run when any referenced table emitted this substep
                and not (
                    cross_nodes
                    and any(len(deltas.get(n.id, ())) for n in cross_nodes)
                )
                # lockstep: exchange-point operators participate in every
                # commit's all-to-all even with no local rows (peers block on
                # our partitions)
                and not (self._cluster is not None and evaluator._cluster_barrier)
            ):
                delta = Delta.empty(self.output_columns_of(node))
            else:
                if (
                    self._undo_current is not None
                    and node.id not in self._undo_current["evals"]
                ):
                    # pre-mutation snapshot, taken the FIRST time this
                    # operator runs in the commit (the neu phase re-runs
                    # nodes; the undo target is the pre-commit state)
                    self._capture_undo_state(node, evaluator)
                if self._cluster is not None and any(
                    p is not None for p in evaluator._cluster_policies
                ):
                    inputs = self._route_cluster_inputs(node, evaluator, inputs)
                if originates:
                    delta = evaluator.drain_neu(inputs)
                else:
                    try:
                        delta = evaluator.process(inputs)
                    except Exception as exc:
                        from pathway_tpu.internals.trace import add_error_context
                        from pathway_tpu.parallel.cluster import (
                            PeerShutdownError,
                            PeerTimeoutError,
                        )

                        if isinstance(exc, (PeerShutdownError, PeerTimeoutError)):
                            # a peer death inside this node's exchange is an
                            # infrastructure failure, not an operator bug:
                            # keep it TYPED so the surgical-rejoin fence (and
                            # isinstance-based failure triage) can catch it
                            raise
                        raise add_error_context(exc, node) from exc
            if neu and len(delta):
                delta.neu = True
        deltas[node.id] = delta
        if len(delta):
            any_output = True
            self._step_counts[node.id] = self._step_counts.get(node.id, 0) + len(delta)
            if node.output is not None and node.id in self._materialized:
                if self._undo_current is not None:
                    # applied-delta record: Delta.negated() of each entry
                    # (in reverse) is the exact state-table undo
                    self._undo_current["applied"].append((node.id, delta))
                self.states[node.id].apply(delta)
        if profile_ops is not None:
            rows = len(delta)
            # count_nonzero: ONE pass over diffs (a min() pre-check reads
            # the array twice on the update-heavy deltas that dominate
            # steady state, doubling the per-op profiling cost)
            retractions = (
                int(np.count_nonzero(delta.diffs < 0)) if rows else 0
            )
            profile_ops.append((
                node.id,
                node.name,
                node.kind,
                time_mod.perf_counter() - _t_op,
                rows,
                retractions,
                neu,
            ))
        return any_output

    def _route_cluster_inputs(
        self, node: pg.Node, evaluator: Any, inputs: List[Delta]
    ) -> List[Delta]:
        """Apply the evaluator's per-input cluster policies (all-to-all barriers;
        every process reaches this point each commit — ``_cluster_barrier``)."""
        routed: List[Delta] = []
        for idx, delta in enumerate(inputs):
            policy = evaluator._cluster_policies[idx]
            tag = f"{self.current_time}:{node.id}:i{idx}".encode()
            if policy is None:
                routed.append(delta)
            elif policy == "rowkey":
                routed.append(self._cluster.exchange_delta(tag, delta, delta.keys))
            elif policy == "custom":
                route_keys = (
                    delta.keys if len(delta) == 0
                    else evaluator.cluster_route_keys(idx, delta)
                )
                routed.append(self._cluster.exchange_delta(tag, delta, route_keys))
            elif policy == "root":
                routed.append(self._cluster.exchange_to_root(tag, delta))
            elif policy == "broadcast":
                routed.append(self._cluster.broadcast_merge(tag, delta))
            else:
                raise AssertionError(f"unknown cluster policy {policy!r}")
        return routed

    def health(self) -> Dict[str, Any]:
        """One liveness payload, two consumers: the ``/healthz`` endpoint and
        the supervisor's per-rank status file (``parallel/supervisor.py``)."""
        peers: Dict[str, float] = {}
        dead: Dict[str, str] = {}
        if self._cluster is not None:
            ages = getattr(self._cluster, "heartbeat_ages", None)
            if ages is not None:
                peers = {str(p): round(a, 3) for p, a in ages().items()}
            dead_fn = getattr(self._cluster, "dead_peers", None)
            if dead_fn is not None:
                dead = {str(p): r for p, r in dead_fn().items()}
        return {
            "rank": self._rank,
            "commit": self._commit,
            "persistence": self._persistence is not None,
            "peers": peers,
            "dead_peers": dead,
            # surgical-restart observability: which mesh incarnation this rank
            # is on, how often it (or its cluster) was relaunched, and whether
            # it is currently quiesced at an epoch fence
            "epoch": getattr(self._cluster, "epoch", 0)
            if self._cluster is not None
            else 0,
            "restarts": int(os.environ.get("PATHWAY_RESTART_COUNT", "0") or 0),
            "rejoins": self._rejoins,
            "last_rejoin_s": self._last_rejoin_s,
            "state": self._rejoin_state,
            # recovery-SLO observability: the commit the last durable cluster
            # checkpoint covers, and how many journal frames a recovery would
            # still replay past it — together they bound the next rejoin
            "checkpoint_commit": self._manifest_commit,
            "journal_tail_frames": (
                self._persistence.frames_since_compact
                if self._persistence is not None
                else None
            ),
            # elastic-membership observability: where the topology is and
            # where it is going (stable|joining|draining|resharding|drained)
            "membership_state": self._membership_state,
            "current_workers": (
                getattr(self._cluster, "n", None)
                if self._cluster is not None
                else 1
            ),
            "target_workers": (
                self._member_pending.target_n
                if self._member_pending is not None
                else self._target_workers
            ),
            "membership_committed": self._member_committed_gen,
            "membership_refused": self._member_refused,
            "membership_refusals": self._member_refusal_nodes,
            "manifest_workers": self._mismatch_workers,
            # autoscale observability: this rank's published load signals and
            # the mirrored controller state (flap-lock visible in /healthz)
            "autoscale": _autoscale_signals(
                input_rows=(
                    self.prober_stats.input_rows
                    if self.prober_stats is not None
                    else None
                )
            ),
            "autoscaler": self._autoscale_state,
        }

    # -- elastic mesh membership (MEMBERSHIP_CHANGE; parallel/membership.py) ---

    def _membership_vote(self) -> "tuple | None":
        """Per-commit membership vote riding the neu allgather: the directive
        this rank has seen (so peers that have not read the file yet learn it
        FROM the vote) plus this rank's quiesce readiness."""
        cluster = self._cluster
        if (
            cluster is None
            or not getattr(cluster, "supports_rejoin", False)
            or self._supervise_dir is None
            or self._persistence is None
            or not self._persistence.supports_cluster_checkpoints
        ):
            return None
        now = time_mod.monotonic()
        if now - getattr(self, "_member_poll_at", 0.0) >= 0.25:
            self._member_poll_at = now
            from pathway_tpu.parallel.membership import read_directive

            d = read_directive(self._supervise_dir)
            if (
                d is not None
                and d.generation > self._member_done_gen
                and d.target_n != cluster.n
                and (
                    self._member_pending is None
                    or d.generation > self._member_pending.generation
                )
            ):
                self._member_pending = d
                self._member_attempts = 0
        if self._member_pending is None:
            return None
        return (self._member_pending.as_tuple(), self._membership_ready())

    def _membership_ready(self) -> bool:
        """Quiesce check: every reshardable live source paused at a scan
        boundary with nothing buffered and no segment in flight. Rank-local
        sources keep flowing — their rows stay where they are ingested."""
        self._membership_state = (
            "draining"
            if self._member_pending is not None
            and self._rank >= self._member_pending.target_n
            else "resharding"
        )
        ready = True
        for node, _ev in self._sources:
            source = node.config["source"]
            if source.is_finished():
                continue
            subject = getattr(source, "subject", None)
            if getattr(subject, "reshard_exports", None) is None:
                continue
            subject.reshard_pause()
            if not subject.reshard_idle(0.05):
                ready = False
                continue
            if not source.reshard_ready():
                ready = False
        return ready

    def _membership_unpause(self) -> None:
        for node, _ev in self._sources:
            subject = getattr(node.config["source"], "subject", None)
            resume = getattr(subject, "reshard_resume", None)
            if resume is not None:
                resume()

    def _membership_votes_seen(self, mvotes: "List[tuple | None]") -> None:
        """Fold the allgathered membership votes: adopt the newest directive
        and arm the transition when every rank is ready for the same
        generation."""
        from pathway_tpu.parallel.membership import MembershipDirective

        self._member_all_ready = False
        best: "tuple | None" = None
        for mv in mvotes:
            if mv is not None and (best is None or mv[0][0] > best[0]):
                best = mv[0]
        if best is None:
            return
        gen = int(best[0])
        if gen > self._member_done_gen and (
            self._member_pending is None
            or self._member_pending.generation < gen
        ):
            self._member_pending = MembershipDirective.from_tuple(best)
            self._member_attempts = 0
        if (
            self._member_pending is not None
            and self._member_pending.generation == gen
            and all(mv is not None and mv[0][0] == gen and mv[1] for mv in mvotes)
        ):
            self._member_all_ready = True

    def _membership_abort(
        self, directive: Any, reason: str, *, permanent: bool
    ) -> None:
        import logging

        from pathway_tpu.engine import telemetry
        from pathway_tpu.internals.config import env_float as _env_float

        telemetry.stage_add("cluster.reshard_aborts")
        log = logging.getLogger("pathway_tpu")
        if permanent or self._member_attempts >= max(
            1,
            int(_env_float("PATHWAY_MEMBERSHIP_MAX_ATTEMPTS", 3)),
        ):
            log.error(
                "rank %d: membership change to n=%d REFUSED (generation %d): %s",
                self._rank, directive.target_n, directive.generation, reason,
            )
            self._member_refused = (directive.generation, reason)
            self._member_done_gen = directive.generation
            self._member_pending = None
        else:
            log.warning(
                "rank %d: membership attempt %d to n=%d aborted (%s); will retry",
                self._rank, self._member_attempts, directive.target_n, reason,
            )
        self._membership_state = "stable"
        self._membership_unpause()
        self._publish_status(force=True)

    def _run_membership_transition(self) -> None:
        """The MEMBERSHIP_CHANGE state machine at a fully quiesced commit
        boundary (modeled first as ``membership_model`` in
        ``internals/protocol_models.py`` — the phases and their order follow
        the model exactly): preflight capability vote → handoff fragments
        (read-back verified) → durability-ack barrier → rank 0 commits the
        membership manifest (the atomic commit point) → journal compaction →
        final old-topology barrier → leavers release / members rewire +
        reset + import → install barrier with the joiners. A crash at ANY
        point either aborts cleanly (pre-manifest: the previous topology
        stands) or completes via restart-all at the new topology (the
        supervisor adapts -n off the typed mismatch reports)."""
        import logging

        from pathway_tpu.engine import telemetry
        from pathway_tpu.engine.profile import histogram
        from pathway_tpu.parallel import membership as ms

        directive = self._member_pending
        self._member_all_ready = False
        if directive is None:
            return
        cluster = self._cluster
        log = logging.getLogger("pathway_tpu")
        commit = self._commit - 1  # the just-completed, fully journaled commit
        gen = directive.generation
        old_n, new_n = cluster.n, directive.target_n
        leaving = self._rank >= new_n
        t0 = time_mod.monotonic()
        self._member_attempts += 1
        self._member_in_flight = True
        self._membership_state = "draining" if leaving else "resharding"
        telemetry.stage_add("cluster.reshard_attempts")
        # quiesce window: the commit loop is paused from here until resume —
        # the REST plane sheds with 429 + the expected remaining pause as an
        # honest Retry-After instead of letting clients hang on a paused
        # engine (engine/brownout.py; chaos-tested)
        from pathway_tpu.engine.brownout import get_brownout
        from pathway_tpu.engine.profile import histograms as _histograms

        _reshard_hist = _histograms().get("pathway_reshard_duration_seconds")
        get_brownout().enter_quiesce(
            _reshard_hist.quantile(0.5)
            if _reshard_hist is not None and _reshard_hist.count
            else 1.0
        )
        if self._recorder is not None:
            self._recorder.record_event(
                "membership",
                phase="begin",
                generation=gen,
                from_n=old_n,
                to_n=new_n,
                commit=commit,
                epoch=getattr(cluster, "epoch", 0),
            )
        self._publish_status(force=True)
        if self._chaos is not None:
            self._chaos.begin_scale_attempt()
            # a donor/leaver killed after the quiesce vote, before its
            # fragments are durable — the headline mid-handoff crash
            self._chaos.maybe_scale_kill(
                self._rank, "scale_drain_kill", generation=gen, commit=commit
            )
        try:
            # 1. preflight capability vote: can every rank re-partition all
            #    of its state? Any refusal aborts BEFORE anything mutates.
            plan = ms.compute_reshard_plan(self)
            refusals = list(plan.refusals)
            refusal_nodes = list(plan.refused_nodes)
            for sref in ms.preflight_sources(self, new_n, self._rank):
                refusals.append(sref)
                refusal_nodes.append(
                    {"node": None, "kind": "input", "reason": sref}
                )
            if self._chaos is not None and self._chaos.scale_fault(
                "scale_refused", self._rank
            ):
                # deterministic refusal injection: the autoscaler's typed
                # refusal-backoff path is exercised without needing a
                # non-reshardable graph in the test program
                refusals.append(
                    "chaos: injected preflight refusal (scale_refused)"
                )
                refusal_nodes.append(
                    {"node": None, "kind": "chaos", "reason": "scale_refused"}
                )
            # refusal observability: per-node reasons on /healthz + the
            # status file, a counter, and a flight event naming the kinds
            self._member_refusal_nodes = refusal_nodes
            if refusals:
                telemetry.stage_add("cluster.preflight_refuse")
                if self._recorder is not None:
                    self._recorder.record_event(
                        "preflight_refuse",
                        generation=gen,
                        kinds=sorted(
                            {str(r.get("kind")) for r in refusal_nodes}
                        ),
                        refusals=len(refusals),
                    )
            ok_votes = cluster.allgather(
                f"member:ready:{gen}:{commit}".encode(),
                refusals[0] if refusals else None,
            )
            bad = [r for r in ok_votes if r is not None]
            if bad:
                self._membership_abort(directive, bad[0], permanent=True)
                return
            # 2. handoff fragments: the reshard as an array redistribution —
            #    every keyed state array partitioned by its owner function
            #    and written per new owner, read-back verified. The default
            #    CHUNKED transport streams bounded mini-fragments (composed
            #    collective steps), keeping a donor's peak handoff memory
            #    O(chunk x peers); PATHWAY_RESHARD_TRANSPORT=gather restores
            #    the whole-fragment path (escape hatch + bench baseline).
            status = "ok"
            stats: Dict[str, int] = {"rows_handed_off": 0}
            frag_bytes = 0
            transport = (
                os.environ.get("PATHWAY_RESHARD_TRANSPORT", "chunked")
                .strip()
                .lower()
            )
            try:
                if transport == "gather":
                    fragments, stats = ms.build_fragments(
                        self, plan, new_n, commit, gen
                    )
                    frag_bytes = self._persistence.dump_reshard_fragments(
                        self._graph_sig, commit, fragments
                    )
                else:
                    chunk_iter, stats = ms.build_fragment_chunks(
                        self, plan, new_n, commit, gen
                    )
                    frag_bytes = self._persistence.dump_reshard_chunks(
                        self._graph_sig, commit, chunk_iter
                    )
            except (ConnectionError, OSError, ValueError) as exc:
                status = f"transient: {exc}"
            acks = cluster.allgather(f"member:ack:{gen}".encode(), status)
            if any(a != "ok" for a in acks):
                self._membership_abort(
                    directive,
                    next(a for a in acks if a != "ok"),
                    permanent=False,
                )
                return
            # 3. the atomic commit point: rank 0 commits the membership
            #    manifest (workers = new_n), read-back verified
            ok0 = True
            if self._rank == 0:
                ok0 = self._persistence.commit_membership_manifest(
                    self._graph_sig,
                    commit,
                    epoch=directive.epoch,
                    from_n=old_n,
                    to_n=new_n,
                    generation=gen,
                )
                if ok0:
                    # supervisor-visible commit marker: a crash from here on
                    # recovers at the NEW topology
                    self._member_committed_gen = gen
                    self._publish_status(force=True)
            oks = cluster.allgather(f"member:done:{gen}".encode(), bool(ok0))
            if not all(oks):
                self._membership_abort(
                    directive, "membership manifest commit failed (torn write)",
                    permanent=False,
                )
                return
            # 4. committed: adopt the new worker count for every later
            #    journal header/snapshot/manifest, and compact this shard
            #    (frames <= C are subsumed by the fragments; compaction is
            #    FORCED — the manifest+tail handoff contract depends on it)
            self._manifest_commit = commit
            self._member_committed_gen = gen
            self._persistence.set_workers(new_n)
            self._persistence.compact_journal(self._graph_sig)
            self._persistence.cleanup_cluster_checkpoints(commit)
            # any previously restored park is superseded by the fragments
            # (leavers write their NEW park after this point, at release)
            self._persistence.clear_source_park()
            cluster.prune_commit_log(commit)
            self._undo_current = None
            self._last_checkpoint = time_mod.monotonic()
            # 5. final old-topology barrier: nobody tears down or rewires
            #    until every old rank is past the commit point
            cluster.allgather(f"member:cut:{gen}".encode(), None)
            rows_out = int(stats.get("rows_handed_off", 0))
            telemetry.stage_add_many({
                "cluster.reshard_rows_handed_off": float(rows_out),
                "cluster.reshard_fragment_bytes": float(frag_bytes),
            })
            if leaving:
                # 6L. leaver release: fragments durable + manifest committed
                #     (the model's release-after-drain invariant). Park the
                #     rank-local source continuation for a future joiner
                #     reusing this rank id, retract delivered rows from the
                #     live sinks, and leave the mesh.
                park = {
                    nid: {
                        k: v
                        for k, v in offs.items()
                        if k != "state_deltas"
                    }
                    for nid, offs in (
                        (node.id, node.config["source"].offset_state())
                        for node, _ev in self._sources
                    )
                }
                self._persistence.dump_source_park(
                    self._graph_sig, commit, {"offsets": park}
                )
                self._deliver_sink_retractions()
                self._membership_state = "drained"
                self._membership_left = True
                self._publish_status(force=True)
                cluster.leave_membership()
                duration = time_mod.monotonic() - t0
                telemetry.stage_add("cluster.reshard_drained")
                log.warning(
                    "rank %d: drained for scale-down to n=%d (generation %d) "
                    "in %.2fs — %d row(s) handed off",
                    self._rank, new_n, gen, duration, rows_out,
                )
                if self._recorder is not None:
                    self._recorder.record_event(
                        "membership", phase="drained", generation=gen,
                        to_n=new_n, duration_s=round(duration, 3),
                    )
                return
            # 6S. survivor: retract EVERYTHING previously delivered while the
            #     old state is still present — step 9 re-delivers the full
            #     imported snapshot, so sinks see one clean retract/re-add
            #     cycle (diff-folding consumers net exactly; retracting only
            #     the moved rows would double-deliver the kept ones)
            self._deliver_sink_retractions()
            # 7. rewire the mesh: install joiner links / cut leaver links,
            #    adopt the new epoch (stale frames purge; future-epoch frames
            #    from faster members deliver — the model's install step)
            cluster.apply_membership(
                new_n,
                directive.epoch,
                on_wait=lambda: self._publish_status(force=True),
            )
            # 8. flip the process-wide topology: connectors and late
            #    PersistenceManager readers see the new count
            os.environ["PATHWAY_PROCESSES"] = str(new_n)
            # 9. sources adopt the new shard map (moved scan state dropped
            #    WITHOUT retractions, gained scan state absorbed), then
            #    evaluator/state-table state resets and re-imports this
            #    rank's fragments — the live path and the crash-recovery
            #    path share one loader
            my_frags = self._persistence.load_reshard_fragments(
                self._graph_sig, commit, self._rank, old_n
            )
            _offs, gained = ms.merge_fragment_sources(my_frags)
            for node, _ev in self._sources:
                source = node.config["source"]
                subject = getattr(source, "subject", None)
                if getattr(subject, "reshard_apply", None) is not None:
                    subject.reshard_apply(new_n, self._rank)
                    source.reshard_scrub(new_n, self._rank)
                deltas = gained.get(node.id)
                if deltas:
                    source.reshard_absorb(deltas)
            self._reset_operator_state()
            ms.import_fragments(self, my_frags)
            self._deliver_sink_snapshots()
            self._membership_unpause()
            # 10. install barrier with the joiners (their setup blocks on it)
            cluster.allgather(f"member:install:{gen}".encode(), None)
            self._commit = commit + 1
            self._member_done_gen = gen
            self._member_pending = None
            self._membership_state = "stable"
            self._target_workers = new_n
            # loop realignment: this transition ran INSIDE step(C); a joiner's
            # first action is a full step(C+1), so this member must go
            # straight to step(C+1) too — the run loop skips its done-vote
            # for this iteration
            self._member_resumed = True
            duration = time_mod.monotonic() - t0
            histogram("pathway_reshard_duration_seconds").observe(duration)
            telemetry.stage_add("cluster.reshard_applied")
            if self._recorder is not None:
                self._recorder.record_event(
                    "membership",
                    phase="applied",
                    generation=gen,
                    from_n=old_n,
                    to_n=new_n,
                    epoch=getattr(cluster, "epoch", 0),
                    duration_s=round(duration, 3),
                    rows_handed_off=rows_out,
                )
            log.warning(
                "rank %d: membership transition to n=%d complete (generation "
                "%d, epoch %d) in %.2fs — %d row(s) handed off, %d fragment "
                "byte(s)",
                self._rank, new_n, gen, getattr(cluster, "epoch", 0),
                duration, rows_out, frag_bytes,
            )
            self._publish_status(force=True)
        finally:
            import sys as _sys

            get_brownout().exit_quiesce()
            if _sys.exc_info()[0] is None:
                self._member_in_flight = False
            else:
                # an exception is unwinding: LEAVE the in-flight flag set so
                # _surgical_rejoin declines (a mid-transition peer death must
                # reach the supervisor typed — it restarts all at whichever
                # topology committed), and leave a visible trace first
                self._publish_status(force=True)

    def _deliver_sink_retractions(self) -> None:
        """Feed each live sink a retraction of EVERY row it was delivered
        (its input's full pre-transition state). Paired with the
        post-import snapshot delivery this gives sinks one clean
        retract/re-add cycle across the reshard: diff-folding consumers net
        exactly, rows that moved re-appear at their new owner, and rows
        that stayed are re-asserted — the same contract restored
        checkpoints already give sinks."""
        from pathway_tpu.engine.evaluators import OutputEvaluator

        if not self.replay_outputs:
            return
        for node in self._nodes:
            evaluator = self.evaluators.get(node.id)
            if not isinstance(evaluator, OutputEvaluator):
                continue
            inp = node.inputs[0]._node
            state = self.states.get(inp.id)
            if state is None or inp.id not in self._materialized:
                continue
            snap = state.snapshot()
            if not len(snap):
                continue
            retraction = Delta(
                snap.keys,
                -np.ones(len(snap), dtype=np.int64),
                dict(snap.columns),
            )
            evaluator.process([retraction])

    # -- surgical single-rank restart (epoch fence; parallel/cluster.py) -------

    def _surgical_rejoin(self, exc: BaseException) -> bool:
        """Recover from a typed peer failure without dying: quiesce at the
        epoch fence, wait for the supervisor's replacement rank to re-dial,
        roll this rank's operator state back to its own journal shard, and
        lockstep-replay the union of journaled commit ids so every rank —
        survivors and replacement alike — converges on the last cluster-wide
        committed state. Output stays bit-identical to a failure-free run: the
        interrupted commit's drained-but-unjournaled input rows are carried
        across the fence and re-ingested exactly once.

        Returns False when surgical recovery is off or impossible — no
        persistence journal (nothing to roll back to: refused loudly, the
        caller re-raises the typed error within the barrier deadline), a
        thread-mode exchange, replay in progress — or when the fence itself
        fails (second death, no replacement in time): the caller re-raises and
        the supervisor escalates to restart-all, then loud teardown."""
        cluster = self._cluster
        if (
            not self._surgical
            or cluster is None
            or not getattr(cluster, "supports_rejoin", False)
            or self._supervise_dir is None
            or self._persistence is None
            or self._inject is not None
            # a peer death INSIDE a membership transition cannot be healed by
            # a single-rank rejoin (the topology itself is in flight): die
            # typed, the supervisor restarts all at whichever topology the
            # membership manifest committed
            or self._member_in_flight
        ):
            return False
        import logging

        log = logging.getLogger("pathway_tpu")
        t0 = time_mod.monotonic()
        self._rejoin_state = "fencing"
        log.warning(
            "rank %d: peer failure at commit %d (%s); quiescing at the epoch "
            "fence for a surgical rejoin",
            self._rank,
            self._commit,
            exc,
        )
        if self._recorder is not None:
            # the interrupted commit is the post-mortem's subject: dump before
            # the rollback resets state (a failed rejoin dies typed after this)
            self._recorder.record_event(
                "fence",
                commit=self._commit,
                epoch=getattr(cluster, "epoch", 0),
                error=str(exc),
            )
            self._recorder.dump("fence")
        # preserve the interrupted commit's drained input rows IFF its journal
        # frame never made it to disk — journaled rows replay from the journal,
        # carrying them too would double-ingest
        if (
            self._input_deltas_commit == self._commit
            and getattr(self._persistence, "last_commit_id", None) != self._commit
        ):
            for nid, delta in self._input_deltas.items():
                if len(delta):
                    prev = self._rejoin_carry.get(nid)
                    self._rejoin_carry[nid] = (
                        Delta.concat([prev, delta], list(delta.columns.keys()))
                        if prev is not None and len(prev)
                        else delta
                    )
            for node, _ in self._sources:
                rewind = getattr(node.config["source"], "rewind_frame_state", None)
                if rewind is not None:
                    # segment markers drained by the aborted commit re-ride the
                    # next journaled frame
                    rewind()
        # the interrupted commit's partial serve-log entry must never be
        # replayed to a peer (its tags are regenerated live after recovery)
        discard_log = getattr(cluster, "discard_open_commit_log", None)
        if discard_log is not None:
            discard_log()
        from pathway_tpu.parallel.cluster import PeerShutdownError, PeerTimeoutError

        try:
            cluster.begin_fence()
            cluster.await_rejoin(on_wait=lambda: self._publish_status(force=True))
        except (PeerShutdownError, PeerTimeoutError, OSError) as fence_exc:
            self._rejoin_state = "running"
            log.error(
                "rank %d: surgical rejoin failed (%s); dying typed so the "
                "supervisor can degrade to restart-all or tear down",
                self._rank,
                fence_exc,
            )
            return False
        self._rejoin_state = "rejoining"
        self._publish_status(force=True)
        # Recovery rungs, cheapest first (escalation: rewind → checkpoint+tail
        # replay → full journal replay; the supervisor's restart-all and loud
        # teardown sit below). The journal was compacted at the last cluster
        # checkpoint, so reload() and the replay union are bounded by the tail.
        frames = self._persistence.reload(self._graph_sig)
        manifest = self._persistence.load_cluster_manifest(self._graph_sig)
        base: "int | None" = None
        if manifest is not None:
            base = int(manifest["commit_id"])
            self._manifest_commit = base
            # belt and braces: a crash after the manifest barrier but before
            # this rank's compaction leaves subsumed frames behind
            frames = [f for f in frames if f[0] > base]
        floor = base + 1 if base is not None else 0
        local_frames = {cid: deltas for cid, deltas, _offs in frames}
        all_ids = self._cluster_replay_ids(local_frames)
        from pathway_tpu.engine import telemetry
        from pathway_tpu.internals.config import get_pathway_config

        # Rung coordination. Serving logged barrier parts is only equivalent to
        # step-replaying a tail commit when every rank's live inputs for that
        # commit made it into a journal frame. A survivor interrupted mid-commit
        # BEFORE journaling carries its drained rows across the fence instead —
        # if a peer still journaled that commit (barrier skew of one commit is
        # possible: the dead rank's last sends can reach one survivor and not
        # another), the replayed commit diverges from the logged one, and
        # everyone must step-replay from a reset. Each rank votes the id of its
        # unjournaled interrupted commit (None when clean); any vote naming a
        # journaled tail commit forces rung 2 cluster-wide. The vote is a
        # dedicated barrier so replacements (which always step) stay aligned.
        interrupted = (
            self._commit
            if (
                self._input_deltas_commit == self._commit
                and getattr(self._persistence, "last_commit_id", None)
                != self._commit
            )
            else None
        )
        mode_votes = cluster.allgather(b"replay:mode", interrupted)
        tail_clean = all(
            v is None or not all_ids or v > all_ids[-1] for v in mode_votes
        )
        rewound = (
            self._undo_depth > 0
            and self._rewind_safe
            and tail_clean
            # a live in-flight record must be for THIS commit (a mismatch means
            # bookkeeping drifted — reset rather than mis-undo); None is fine:
            # the failure hit between commits, state is complete as-is
            and (
                self._undo_current is None
                or self._undo_current["commit"] == self._commit
            )
            # batch-mode replay collapses frames into one renumbered commit —
            # a shape the per-commit serve log cannot reproduce
            and get_pathway_config().persistence_mode != "batch"
            and cluster.commit_log_covers(all_ids)
        )
        if rewound:
            # rung 1 — incremental rewind: this rank's state is current except
            # for the interrupted commit, which is undone IN PLACE from the
            # retained undo record; the replacement's tail replay is then
            # served from the logged barriers instead of re-stepping anything
            self._undo_interrupted_commit()
            for cid in all_ids:
                cluster.serve_commit_log(cid)
            self._commit = all_ids[-1] + 1 if all_ids else floor
            telemetry.stage_add("cluster.rejoin_rewinds")
        else:
            # rung 2/3 — the interrupted commit left partially-applied state
            # that (here) cannot be unwound in place: reset, restore this
            # rank's snapshot from the latest cluster checkpoint (rung 2; full
            # journal replay when none exists — rung 3), and lockstep-replay
            # the union of journaled tail ids, exactly like a relaunched
            # process — minus the process launch, imports, and source re-scan
            self._undo_current = None
            self._reset_operator_state()
            if base is not None:
                if manifest.get("membership"):
                    # the newest checkpoint is a membership manifest: this
                    # rank's snapshot is its handoff-fragment set
                    from pathway_tpu.parallel.membership import import_fragments

                    import_fragments(
                        self,
                        self._persistence.load_reshard_fragments(
                            self._graph_sig, base, self._rank,
                            int(manifest["membership"]["from_n"]),
                        ),
                    )
                    self._deliver_sink_snapshots()
                else:
                    self._load_checkpoint_state(
                        self._persistence.load_cluster_snapshot(
                            self._graph_sig, base
                        )
                    )
                self._commit = base + 1
            was_ready, self._ready = self._ready, False  # replay parity with setup
            try:
                self._cluster_replay_steps(local_frames, all_ids, floor)
            finally:
                self._ready = was_ready
            telemetry.stage_add("cluster.rejoin_resets")
        self._rejoins += 1
        self._last_rejoin_s = time_mod.monotonic() - t0
        self._rejoin_state = "running"
        from pathway_tpu.engine.profile import histogram

        # recovery-SLO instrumentation: rejoin latency distribution + the
        # journal-tail length this recovery had to cover
        histogram("pathway_rejoin_duration_seconds").observe(self._last_rejoin_s)
        telemetry.stage_add("cluster.rejoin_tail_commits", float(len(all_ids)))
        if self._recorder is not None:
            self._recorder.record_event(
                "rejoin",
                epoch=getattr(cluster, "epoch", 0),
                duration_s=self._last_rejoin_s,
                mode="rewind" if rewound else (
                    "checkpoint+tail" if base is not None else "full-replay"
                ),
                tail_commits=len(all_ids),
            )
        self._publish_status(force=True)
        log.warning(
            "rank %d: rejoined the cluster at epoch %d in %.2fs via %s "
            "(resuming at commit %d, %d tail commit(s))",
            self._rank,
            getattr(cluster, "epoch", 0),
            self._last_rejoin_s,
            "incremental rewind" if rewound else (
                "checkpoint+tail replay" if base is not None else "full journal replay"
            ),
            self._commit,
            len(all_ids),
        )
        return True

    def _capture_undo_state(self, node: Any, evaluator: Any) -> None:
        """Pre-mutation operator snapshot for the incremental-rewind undo
        record. Input evaluators are excluded (a source cannot un-consume;
        the fence's carry re-ingests the interrupted commit's drained rows)
        and output evaluators are stateless sinks — matching the checkpoint
        snapshot's exclusions. Unpicklable or oversized state disables the
        rewind rung permanently for this run; rung 2 (checkpoint + tail
        replay) stays exact."""
        from pathway_tpu.engine.evaluators import (
            InputEvaluator,
            OutputEvaluator,
            UnpicklableStateError,
        )

        if isinstance(evaluator, (InputEvaluator, OutputEvaluator)):
            return
        rec = self._undo_current
        _t0 = time_mod.perf_counter()
        try:
            state = evaluator.state_dict()
        except UnpicklableStateError as exc:
            self._disable_rewind(str(exc))
            return
        rec["capture_s"] += time_mod.perf_counter() - _t0
        rec["evals"][node.id] = state
        rec["bytes"] += sum(len(b) for b in state.values())
        if self._undo_max_bytes and rec["bytes"] > self._undo_max_bytes:
            self._disable_rewind(
                f"per-commit undo state hit PATHWAY_UNDO_MAX_STATE_BYTES "
                f"({rec['bytes']} > {self._undo_max_bytes}); re-pickling this "
                "much state every commit would cost more than the tail replay "
                "it avoids"
            )

    def _disable_rewind(self, reason: str) -> None:
        """Turn the rewind rung off for the rest of this run (the condition —
        unpicklable or oversized operator state — recurs every commit). The
        serve log is dropped too: a rank that must reset on a fence recomputes
        its barrier parts live, so logging them is dead weight."""
        import logging

        logging.getLogger("pathway_tpu").warning(
            "incremental rewind disabled for this run: %s — fences fall back "
            "to checkpoint + journal-tail replay",
            reason,
        )
        self._rewind_safe = False
        self._undo_depth = 0
        self._undo_current = None
        cluster = self._cluster
        if cluster is not None and hasattr(cluster, "discard_open_commit_log"):
            cluster.discard_open_commit_log()
            cluster.prune_commit_log(self._commit)
            cluster.commit_log_depth = 0
        from pathway_tpu.engine import telemetry

        telemetry.stage_add("cluster.rewind_disabled")

    def _undo_interrupted_commit(self) -> None:
        """Rung-1 rollback: invert the interrupted commit's applied state-table
        deltas (in reverse order) and restore the pre-mutation evaluator
        snapshots captured before each operator ran. Exact by construction —
        ``Delta.negated()`` of an applied delta removes precisely the rows it
        inserted and re-inserts the rows it retracted (retraction rows carry
        their values). A COMPLETED commit never reaches here: its record is
        dropped the moment its mutations become final (see ``step``)."""
        rec, self._undo_current = self._undo_current, None
        if rec is None or rec["commit"] != self._commit:
            return  # the failure hit between commits: nothing was applied
        for nid, delta in reversed(rec["applied"]):
            self.states[nid].apply(delta.negated())
        for nid, blobs in rec["evals"].items():
            self.evaluators[nid].load_state_dict(blobs)
        self._substep_deltas = {}
        self._input_deltas = {}
        self._input_deltas_commit = -1
        self._step_counts = {}
        from pathway_tpu.engine import telemetry

        telemetry.stage_add("cluster.commits_rewound")

    def _reset_operator_state(self) -> None:
        """Discard every evaluator and state table and rebuild them pristine
        from the graph (the rejoin rollback: in-memory state from the
        interrupted epoch is unrecoverable once a commit half-applied).
        Sources are NOT reset — a survivor's connectors are live and correctly
        positioned; everything they ever emitted is either journaled (replays)
        or carried in ``_rejoin_carry``."""
        from pathway_tpu.engine.evaluators import EVALUATORS

        self.evaluators = {}
        self.states = {}
        for node in self._nodes:
            self.evaluators[node.id] = EVALUATORS[type(node)](node, self)
            columns = node.output.column_names() if node.output is not None else []
            self.states[node.id] = StateTable(columns)
        self._bind_cluster_policies()
        self._sources = [(node, self.evaluators[node.id]) for node, _ in self._sources]
        self._materialized = self._compute_materialized()
        self._substep_deltas = {}
        self._input_deltas = {}
        self._input_deltas_commit = -1
        self._step_counts = {}

    def output_columns_of(self, node: pg.Node) -> List[str]:
        return node.output.column_names() if node.output is not None else []

    def sources_finished(self) -> bool:
        return all(node.config["source"].is_finished() for node, _ in self._sources)

    def primary_sources_finished(self) -> bool:
        return all(
            node.config["source"].is_finished()
            for node, _ in self._sources
            if not getattr(node.config["source"], "loopback", False)
        )

    def subtree_closed(self, node: pg.Node) -> bool:
        """Frontier check: True when ``node``'s operator subtree can emit no further
        delta in any future commit (all ancestor sources finished, no pending operator
        state anywhere in the subtree). The TPU-native stand-in for the reference's
        frontier tracking (timely progress; ``TotalFrontier``, ``src/engine/frontier.rs``):
        downstream operators use it to stop maintaining state that can never be probed
        again. Conservative: returns False under journal replay, persistence, cluster
        mode, and nested iterate runners, where closure is not locally decidable."""
        if (
            self._materialize_all
            or self._inject is not None
            or self._persistence is not None
            or self._cluster is not None
        ):
            return False
        cache = getattr(self, "_closed_cache", None)
        if cache is None or cache[0] != self._commit:
            cache = (self._commit, {})
            self._closed_cache = cache
        memo = cache[1]
        if node.id in memo:
            return memo[node.id]
        memo[node.id] = False  # cycle guard (loop-back chains stay open)
        closed = True
        if isinstance(node, pg.InputNode):
            closed = node.config["source"].is_finished()
        else:
            evaluator = self.evaluators.get(node.id)
            if evaluator is not None and (
                _has_pending(evaluator)
                or getattr(evaluator, "neu_pending", _no_pending)()
            ):
                closed = False
            else:
                closed = all(self.subtree_closed(inp._node) for inp in node.inputs)
        memo[node.id] = closed
        return closed

    def _ancestor_inputs(self, node: pg.Node) -> list:
        """Transitive InputNodes feeding ``node`` (memoized)."""
        cache = getattr(self, "_ancestor_cache", None)
        if cache is None:
            cache = self._ancestor_cache = {}
        if node.id in cache:
            return cache[node.id]
        cache[node.id] = []  # cycle guard (loop-back chains)
        out: list = []
        if isinstance(node, pg.InputNode):
            out.append(node)
        for inp in node.inputs:
            out.extend(self._ancestor_inputs(inp._node))
        cache[node.id] = out
        return out

    def _notify_stream_end(self) -> None:
        """Deliver on_end to each subscriber whose ENTIRE input ancestry is final —
        including loop-back sources, so a subscriber downstream of an
        AsyncTransformer hears the end only after in-flight invocations drained
        (and a chained transformer closes cascade-style). Re-checked every idle
        iteration; each evaluator fires once."""
        from pathway_tpu.engine.evaluators import OutputEvaluator

        for node in self._nodes:
            evaluator = self.evaluators.get(node.id)
            if not isinstance(evaluator, OutputEvaluator):
                continue
            if all(
                a.config["source"].is_finished() for a in self._ancestor_inputs(node)
            ):
                evaluator.notify_stream_end()

    def has_pending(self) -> bool:
        return any(_has_pending(e) for e in self.evaluators.values())

    def finish(self) -> None:
        from pathway_tpu.engine.evaluators import OutputEvaluator, WithUniverseOfEvaluator

        for node, _ in self._sources:
            # graceful producer shutdown (streaming subjects poll this between
            # refresh cycles — e.g. the airbyte sync loop)
            subject = getattr(node.config["source"], "subject", None)
            stop = getattr(subject, "stop", None)
            if stop is not None:
                stop()
        for node in self._nodes:
            evaluator = self.evaluators.get(node.id)
            if isinstance(evaluator, OutputEvaluator):
                if not self._shared_nonroot:
                    # transparent-threads rank > 0 shares rank 0's sink objects;
                    # only rank 0 may fire their on_end notifications
                    evaluator.finish()
            elif isinstance(evaluator, WithUniverseOfEvaluator):
                evaluator.verify_universes()
        if self._persistence is not None:
            self._persistence.close()
        if self._monitor is not None:
            self._monitor.close()
        if self._http_server is not None:
            self._http_server.close()
            self._http_server = None
        # stop idle encoder-service workers (drain + join): teardown must not
        # leave a device-owning thread behind a finished run — services stay
        # usable, the worker respawns lazily on the next submit. Module never
        # imported = no services exist = nothing to stop.
        import sys as _sys

        svc_mod = _sys.modules.get("pathway_tpu.models.encoder_service")
        if svc_mod is not None:
            try:
                svc_mod.stop_all_workers()
            except Exception:
                pass
        # final trace flush (no-op when tracing is off or no dir is known);
        # crash/fence/chaos paths flush via the flight recorder's dump instead
        trace_path = _tracing.get_tracer().flush(reason="finish")
        if trace_path is not None and self._recorder is not None:
            self._recorder.record_event("trace_flush", path=trace_path)

    def _lint_gate(self, *, persistence: bool) -> None:
        """Automatic graph lint before the first commit, gated by
        ``PATHWAY_LINT=off|warn|error`` (default ``warn``). Diagnostics are
        logged, mirrored into the stage counters + flight recorder, and under
        ``error`` an error-severity finding refuses the run (GraphLintError)."""
        import logging

        # the runtime's OWN concurrency (PWA101-104) and resource/exception
        # (PWA201-205) gates ride here too but are independent knobs:
        # PATHWAY_LINT=off must not disarm them. Both default off — the
        # runtime tree changes with the package, not the user program, so CI
        # runs `cli analyze --runtime` instead of every pw.run paying a
        # re-parse
        from pathway_tpu.analysis import resource_gate, runtime_gate

        runtime_gate()
        resource_gate()
        mode = os.environ.get("PATHWAY_LINT", "warn").strip().lower()
        if mode in ("off", "0", "false", "no", "none", ""):
            return
        if mode not in ("warn", "error"):
            # a typo (PATHWAY_LINT=errors) must not silently disarm the gate
            logging.getLogger("pathway_tpu.analysis").warning(
                "unrecognized PATHWAY_LINT=%r (expected off|warn|error); "
                "falling back to 'warn' — errors will NOT refuse the run",
                mode,
            )
            mode = "warn"
        if getattr(self, "_lint_done", False):
            return
        self._lint_done = True
        from pathway_tpu.analysis import GraphLintError, analyze_graph

        # one DAG walk per runner: the same AnalysisContext feeds the fusion
        # planner in setup() (building two contexts per pw.run was a full
        # duplicate walk of consumer maps + upstream sets)
        report = analyze_graph(
            self.graph,
            persistence=persistence,
            ctx=self._analysis_context(persistence=persistence),
        )
        report.emit_telemetry()
        if report.diagnostics:
            log = logging.getLogger("pathway_tpu.analysis")
            for d in report.errors + report.warnings:
                log.warning("%s", d.format())
            for d in report.infos:
                log.info("%s", d.format())
        if mode == "error" and report.errors:
            raise GraphLintError(report)

    def run(
        self,
        *,
        monitoring_level: Any = None,
        with_http_server: bool = False,
        terminate_on_error: bool = True,
        max_commits: int | None = None,
        persistence_config: Any = None,
        **kwargs: Any,
    ) -> None:
        from pathway_tpu.internals.config import get_pathway_config

        env_cfg = get_pathway_config()
        # persistence may also arrive via the record/replay env contract
        # (PATHWAY_REPLAY_STORAGE, applied below) — the persistence-gated lint
        # passes (PWA002 severity, PWA005) must see it either way
        lint_persistence = persistence_config is not None or bool(
            env_cfg.replay_storage
        )
        lint_exempt = getattr(self, "lint_exempt", False)
        if not lint_exempt and os.environ.get("PATHWAY_LINT_CAPTURE", "") not in (
            "",
            "0",
        ):
            # `cli analyze` build-only mode: the graph is complete, hand it to
            # the analyzer without executing a single commit (debug capture
            # helpers are exempt so the analyzed program runs past them to its
            # real ``pw.run``)
            from pathway_tpu.analysis import GraphCaptureInterrupt

            raise GraphCaptureInterrupt(self.graph, persistence=lint_persistence)
        if not lint_exempt and not self._ready and not self._materialize_all:
            from pathway_tpu.parallel.cluster import (
                in_thread_worker,
                thread_worker_rank,
                thread_worker_shared_inputs,
            )

            if not in_thread_worker():
                self._lint_gate(persistence=lint_persistence)
            elif not thread_worker_shared_inputs() and thread_worker_rank() == 0:
                # run_shared_graph workers re-run the one graph the parent
                # already linted — skip. run_threads workers each build and run
                # their OWN graph with no parent run: rank 0's graph is
                # representative, lint it once instead of N times
                self._lint_gate(persistence=lint_persistence)
        if env_cfg.threads > 1 and not self._ready:
            from pathway_tpu.parallel.cluster import in_thread_worker

            if not in_thread_worker():
                # PATHWAY_THREADS lane: fan this run out over worker threads
                # (one shared graph; sources rank 0, compute key-partitioned,
                # outputs centralized — identical output to a 1-thread run)
                if env_cfg.processes > 1:
                    raise NotImplementedError(
                        "PATHWAY_THREADS > 1 combined with PATHWAY_PROCESSES > 1 "
                        "(thread workers inside each spawned process) needs a "
                        "hierarchical exchange that is not built; use spawn -n "
                        "for multi-process or -t for multi-thread"
                    )
                from pathway_tpu.parallel.threads import run_shared_graph

                run_shared_graph(
                    self.graph,
                    env_cfg.threads,
                    dict(
                        monitoring_level=monitoring_level,
                        with_http_server=with_http_server,
                        terminate_on_error=terminate_on_error,
                        max_commits=max_commits,
                        persistence_config=persistence_config,
                        **kwargs,
                    ),
                )
                return
        if persistence_config is None and env_cfg.replay_storage:
            # `pathway_tpu spawn --record` / `replay` contract (reference cli.py:166-284)
            from pathway_tpu import persistence as _pers

            persistence_config = _pers.Config(
                _pers.Backend.filesystem(env_cfg.replay_storage)
            )
        from pathway_tpu.engine.http_server import ProberStats, maybe_start_http_server

        self.prober_stats = ProberStats()
        self._http_server = maybe_start_http_server(self.prober_stats, with_http_server)
        from pathway_tpu.engine.telemetry import MetricsRecorder, span

        self._metrics = MetricsRecorder.get(self.prober_stats)

        try:
            if not self._ready:
                with span("graph_runner.build", nodes=len(self.graph.nodes)):
                    self.setup(monitoring_level, persistence_config=persistence_config)
        except BaseException as exc:
            from pathway_tpu.parallel.membership import MembershipMismatchError

            if isinstance(exc, MembershipMismatchError):
                # the store committed a membership transition this launch does
                # not match: publish manifest_n so the supervisor adapts -n
                self._mismatch_workers = exc.manifest_n
                self._membership_state = "membership_mismatch"
                self._publish_status(force=True)
            # a failed build must not leak the just-bound monitoring listener:
            # the caller may fix the config and rerun in this same process
            if self._http_server is not None:
                self._http_server.close()
                self._http_server = None
            raise
        if self._http_server is not None:
            self._http_server.health_source = self.health
        if env_cfg.snapshot_access == "replay" and not env_cfg.continue_after_replay:
            # replay-only run: the journal has been fed through the graph in setup();
            # stop without consuming realtime connector data
            self.finish()
            return
        from pathway_tpu.engine import expression_evaluator as ee_mod

        runtime = ee_mod.get_runtime()
        prev_runtime = dict(runtime)
        runtime["terminate_on_error"] = terminate_on_error
        # fallback sink for operators with no local log; nested iterate runners run on
        # this thread and inherit it, while their inner node objects route precisely
        runtime["global_source"] = getattr(self.graph, "_error_log_source", None)
        from pathway_tpu.engine.datasource import StreamingDataSource

        # idle pacing: wake on producer pushes (latency = wake + one commit), with the
        # smallest configured autocommit interval as the staleness cap. The wake event
        # is per-runner so concurrent loops never consume each other's signals.
        idle_wait = 0.010
        for node, _ in self._sources:
            ms = getattr(node.config["source"], "_autocommit_ms", None)
            if ms:
                idle_wait = min(idle_wait, ms / 1000.0)
        import threading as _threading

        wake = _threading.Event()
        StreamingDataSource.register_runner(wake)
        from pathway_tpu.parallel.cluster import PeerShutdownError, PeerTimeoutError

        # flight-recorder SIGTERM hook: a supervisor stall-kill (SIGTERM grace
        # before SIGKILL) or operator shutdown leaves a dump behind. Main
        # thread only — signal.signal raises ValueError elsewhere.
        import signal as _signal

        _prev_term: Any = None
        _installed_term = False
        if self._recorder is not None and self._recorder.enabled:
            def _on_term(signum: int, frame: Any) -> None:
                self._recorder.dump("sigterm")
                # chain: restore whatever was there — including SIG_IGN (a
                # process that deliberately ignored SIGTERM must keep
                # ignoring it) — and re-raise so the previous disposition
                # (default termination, operator handler, or ignore) applies
                _signal.signal(
                    _signal.SIGTERM,
                    _prev_term if _prev_term is not None else _signal.SIG_DFL,
                )
                os.kill(os.getpid(), _signal.SIGTERM)

            try:
                _prev_term = _signal.signal(_signal.SIGTERM, _on_term)
                _installed_term = True
            except ValueError:
                pass  # not the main thread

        commits = 0
        try:
            with span("graph_runner.run"):
                while True:
                    wake.clear()
                    try:
                        any_output = self.step()
                    except (PeerShutdownError, PeerTimeoutError) as exc:
                        # a peer died mid-commit: with surgical mode on, quiesce
                        # at the epoch fence, take the relaunched rank back in,
                        # roll back the interrupted commit, and keep running —
                        # otherwise die typed (PR 2 restart-all/teardown)
                        if self._surgical_rejoin(exc):
                            continue
                        raise
                    if self._membership_left:
                        # this rank drained away in a scale-down: its handoff
                        # is durable, its journal shard compacted empty — a
                        # clean exit the supervisor expects
                        break
                    commits += 1
                    if getattr(self, "_member_resumed", False):
                        # a membership transition completed inside that step:
                        # joiners enter the lockstep loop with a full step at
                        # C+1, so skip this iteration's done-vote and step
                        # again immediately — every member's barrier tag
                        # sequence realigns at commit C+1
                        self._member_resumed = False
                        continue
                    if max_commits is not None and commits >= max_commits:
                        break
                    if (
                        self.primary_sources_finished()
                        and not any_output
                        and not self.has_pending()
                        # cluster peers may still route rows here; finish() notifies
                        and self._cluster is None
                    ):
                        self._notify_stream_end()
                    local_done = (
                        self.sources_finished() and not any_output and not self.has_pending()
                    )
                    if self._cluster is not None:
                        # lockstep shutdown: stop only when EVERY process drained
                        # (a peer's data may still route rows to us)
                        try:
                            done_votes = self._cluster.allgather(
                                f"done:{self._commit}".encode(), local_done
                            )
                        except (PeerShutdownError, PeerTimeoutError) as exc:
                            if self._surgical_rejoin(exc):
                                continue
                            raise
                        if all(done_votes):
                            break
                        if not any_output:
                            # keep stepping (peers may exchange into us), but pace
                            # the idle spin — barriers resume inside the next step
                            wake.wait(timeout=idle_wait)
                        continue
                    if local_done:
                        break
                    if not any_output and not self.sources_finished():
                        wake.wait(timeout=idle_wait)
        except BaseException as exc:
            # a failing run must be distinguishable from a clean close by sinks
            # that hand state to OTHER graphs (ExportedTable._fail) — finish()
            # in the finally block fires their on_end either way
            from pathway_tpu.engine.evaluators import OutputEvaluator
            from pathway_tpu.parallel.membership import MembershipMismatchError

            if isinstance(exc, MembershipMismatchError):
                # report the store's worker count through the status file so
                # the supervisor can ADAPT -n (a membership transition
                # committed before a crash) instead of tearing down
                self._mismatch_workers = exc.manifest_n
                self._membership_state = "membership_mismatch"
                self._publish_status(force=True)
            if self._recorder is not None:
                self._recorder.dump(f"crash: {type(exc).__name__}")
            for evaluator in self.evaluators.values():
                if isinstance(evaluator, OutputEvaluator):
                    evaluator.notify_failure(exc)
            raise
        finally:
            if _installed_term:
                try:
                    _signal.signal(_signal.SIGTERM, _prev_term)
                except (ValueError, TypeError):
                    pass
            StreamingDataSource.unregister_runner(wake)
            runtime.update(prev_runtime)
            if max_commits is None:
                self.finish()
            elif self._http_server is not None:
                # stepped runs keep engine state but must not leak the
                # monitoring listener port across back-to-back runs
                self._http_server.close()
                self._http_server = None


def _has_pending(evaluator: Any) -> bool:
    has = getattr(evaluator, "has_pending", None)
    return bool(has()) if has is not None else False


def _no_pending() -> bool:
    return False


def _make_monitor(level: Any, nodes: List[pg.Node]) -> Any:
    if level is None:
        return None
    from pathway_tpu.internals.monitoring import MonitoringLevel, StatsMonitor

    if level in (MonitoringLevel.NONE, "none"):
        return None
    if isinstance(level, str):
        level = MonitoringLevel(level)
    return StatsMonitor(nodes, level=level)


def run(**kwargs: Any) -> None:
    """Execute the global dataflow graph (parity: ``pw.run``, reference ``run.py:12``)."""
    GraphRunner(pg.G).run(**kwargs)


def run_all(**kwargs: Any) -> None:
    run(**kwargs)
