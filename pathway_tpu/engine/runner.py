"""The commit loop — graph execution driver.

Parity: reference ``pw.run`` path (``internals/run.py`` → ``GraphRunner`` →
``run_with_new_dataflow_graph``'s worker loop ``dataflow.rs:5596-5650``). Instead of timely's
``step_or_park``, each commit gathers one batch per source, pushes deltas through the operator
DAG in topological order, and delivers outputs. Timestamps are even integers (data times), as in
the reference's alt/neu scheme (``timestamp.rs:20``).
"""

from __future__ import annotations

import time as time_mod
from typing import Any, Dict, List, Optional

from pathway_tpu.engine.columnar import Delta, StateTable
from pathway_tpu.internals import parse_graph as pg


class GraphRunner:
    def __init__(self, graph: Any = None):
        self.graph = graph if graph is not None else pg.G
        self.states: Dict[int, StateTable] = {}
        self.evaluators: Dict[int, Any] = {}
        self.current_time = 0
        self._commit = 0
        self._sources: List[tuple] = []
        self._nodes: List[pg.Node] = []
        self._monitor: Any = None
        self._ready = False
        self.draining = False
        self._step_counts: Dict[int, int] = {}

    def state_of(self, node: pg.Node) -> StateTable:
        return self.states[node.id]

    def setup(self, monitoring_level: Any = None) -> None:
        from pathway_tpu.engine.evaluators import EVALUATORS

        self._nodes = list(self.graph.nodes)
        for node in self._nodes:
            if node.id in self.evaluators:
                continue
            evaluator_cls = EVALUATORS.get(type(node))
            if evaluator_cls is None:
                raise NotImplementedError(f"no evaluator for node kind {node.kind!r}")
            self.evaluators[node.id] = evaluator_cls(node, self)
            columns = node.output.column_names() if node.output is not None else []
            self.states[node.id] = StateTable(columns)
        self._sources = [
            (node, self.evaluators[node.id])
            for node in self._nodes
            if isinstance(node, pg.InputNode)
        ]
        for node, evaluator in self._sources:
            node.config["source"].on_start()
        self._monitor = _make_monitor(monitoring_level, self._nodes)
        self._ready = True

    def step(self) -> bool:
        """Run one commit; returns True if any node produced output.

        Each commit runs in two phases mirroring the reference's alt/neu timestamps
        (``dataflow.rs:3447``): the even ("alt") phase moves normal data; the odd ("neu")
        phase moves *forgetting* retractions drained from Forget/AsofNow operators. Keeping
        the phases separate guarantees a delta is never a mix of real updates and
        forgetting updates, so ``_filter_out_results_of_forgetting`` can drop whole neu
        deltas without losing genuine data.
        """
        self.current_time = self._commit * 2  # even data times, as in the reference
        self.draining = self._ready and self.sources_finished()
        any_output = self._substep(neu=False)
        if any(
            getattr(self.evaluators[n.id], "neu_pending", _no_pending)()
            for n in self._nodes
        ):
            self.current_time = self._commit * 2 + 1
            any_output = self._substep(neu=True) or any_output
        if self._monitor is not None:
            self._monitor.update(self._commit, self._step_counts, self.states)
        self._commit += 1
        return any_output

    def _substep(self, *, neu: bool) -> bool:
        if not neu:
            self._step_counts = {}
        deltas: Dict[int, Delta] = {}
        any_output = False
        for node in self._nodes:
            evaluator = self.evaluators[node.id]
            if isinstance(node, pg.InputNode):
                delta = (
                    Delta.empty(self.output_columns_of(node))
                    if neu
                    else evaluator.process([])
                )
            else:
                inputs = [
                    deltas.get(inp._node.id, Delta.empty(inp.column_names()))
                    for inp in node.inputs
                ]
                originates = neu and getattr(evaluator, "neu_pending", _no_pending)()
                if (
                    all(len(d) == 0 for d in inputs)
                    and not originates
                    and not (not neu and _has_pending(evaluator))
                    and node.kind != "iterate_result"
                ):
                    delta = Delta.empty(self.output_columns_of(node))
                elif originates:
                    delta = evaluator.drain_neu(inputs)
                else:
                    delta = evaluator.process(inputs)
                if neu and len(delta):
                    delta.neu = True
            deltas[node.id] = delta
            if len(delta):
                any_output = True
                self._step_counts[node.id] = self._step_counts.get(node.id, 0) + len(delta)
                if node.output is not None:
                    self.states[node.id].apply(delta)
        return any_output

    def output_columns_of(self, node: pg.Node) -> List[str]:
        return node.output.column_names() if node.output is not None else []

    def sources_finished(self) -> bool:
        return all(node.config["source"].is_finished() for node, _ in self._sources)

    def has_pending(self) -> bool:
        return any(_has_pending(e) for e in self.evaluators.values())

    def finish(self) -> None:
        from pathway_tpu.engine.evaluators import OutputEvaluator

        for node in self._nodes:
            evaluator = self.evaluators.get(node.id)
            if isinstance(evaluator, OutputEvaluator):
                evaluator.finish()
        if self._monitor is not None:
            self._monitor.close()

    def run(
        self,
        *,
        monitoring_level: Any = None,
        with_http_server: bool = False,
        terminate_on_error: bool = True,
        max_commits: int | None = None,
        **kwargs: Any,
    ) -> None:
        if not self._ready:
            self.setup(monitoring_level)
        commits = 0
        try:
            while True:
                any_output = self.step()
                commits += 1
                if max_commits is not None and commits >= max_commits:
                    break
                if self.sources_finished() and not any_output and not self.has_pending():
                    break
                if not any_output and not self.sources_finished():
                    time_mod.sleep(0.001)
        finally:
            if max_commits is None:
                self.finish()


def _has_pending(evaluator: Any) -> bool:
    has = getattr(evaluator, "has_pending", None)
    return bool(has()) if has is not None else False


def _no_pending() -> bool:
    return False


def _make_monitor(level: Any, nodes: List[pg.Node]) -> Any:
    if level is None:
        return None
    from pathway_tpu.internals.monitoring import MonitoringLevel, StatsMonitor

    if level in (MonitoringLevel.NONE, "none"):
        return None
    return StatsMonitor(nodes)


def run(**kwargs: Any) -> None:
    """Execute the global dataflow graph (parity: ``pw.run``, reference ``run.py:12``)."""
    GraphRunner(pg.G).run(**kwargs)


def run_all(**kwargs: Any) -> None:
    run(**kwargs)
