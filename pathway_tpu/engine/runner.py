"""The commit loop — graph execution driver.

Parity: reference ``pw.run`` path (``internals/run.py`` → ``GraphRunner`` →
``run_with_new_dataflow_graph``'s worker loop ``dataflow.rs:5596-5650``). Instead of timely's
``step_or_park``, each commit gathers one batch per source, pushes deltas through the operator
DAG in topological order, and delivers outputs. Timestamps are even integers (data times), as in
the reference's alt/neu scheme (``timestamp.rs:20``).
"""

from __future__ import annotations

import time as time_mod
from typing import Any, Dict, List, Optional

from pathway_tpu.engine.columnar import Delta, StateTable
from pathway_tpu.internals import parse_graph as pg


class GraphRunner:
    def __init__(self, graph: Any = None):
        self.graph = graph if graph is not None else pg.G
        self.states: Dict[int, StateTable] = {}
        self.evaluators: Dict[int, Any] = {}
        self.current_time = 0
        self._commit = 0
        self._sources: List[tuple] = []
        self._nodes: List[pg.Node] = []
        self._monitor: Any = None
        self._ready = False
        self.draining = False
        self._step_counts: Dict[int, int] = {}
        self._persistence: Any = None
        self._inject: Optional[Dict[int, Delta]] = None  # journal replay injection
        self._input_deltas: Dict[int, Delta] = {}
        self._dumped_markers: Dict[int, int] = {}
        self.replay_outputs = True

    def state_of(self, node: pg.Node) -> StateTable:
        return self.states[node.id]

    def setup(self, monitoring_level: Any = None, persistence_config: Any = None) -> None:
        from pathway_tpu.engine.evaluators import EVALUATORS

        self._nodes = list(self.graph.nodes)
        for node in self._nodes:
            if node.id in self.evaluators:
                continue
            evaluator_cls = EVALUATORS.get(type(node))
            if evaluator_cls is None:
                raise NotImplementedError(f"no evaluator for node kind {node.kind!r}")
            self.evaluators[node.id] = evaluator_cls(node, self)
            columns = node.output.column_names() if node.output is not None else []
            self.states[node.id] = StateTable(columns)
        self._sources = [
            (node, self.evaluators[node.id])
            for node in self._nodes
            if isinstance(node, pg.InputNode)
        ]
        replay_frames = []
        if persistence_config is not None and persistence_config.backend is not None:
            from pathway_tpu.persistence.engine import PersistenceManager

            self._persistence = PersistenceManager(persistence_config)
            # "silent_replay" keeps external sinks from re-receiving already-delivered
            # rows on resume (in-process subscribers then rebuild state themselves)
            self.replay_outputs = persistence_config.persistence_mode != "silent_replay"
            sig = self.graph.sig()
            replay_frames = self._persistence.load_journal(sig)
            self._persistence.open_for_append(sig)
            if replay_frames:
                self._restore_sources(replay_frames[-1][2])
        for node, evaluator in self._sources:
            node.config["source"].on_start()
        self._monitor = _make_monitor(monitoring_level, self._nodes)
        self._ready = True
        # replay journaled input deltas through the (deterministic) graph to rebuild
        # every operator's state, before any realtime stepping
        for commit_id, input_deltas, _offsets in replay_frames:
            self._inject = input_deltas
            self.step()
        self._inject = None

    def _restore_sources(self, last_offsets: Dict[int, dict]) -> None:
        blob = self._persistence.load_sources()
        states: Dict[int, Any] = {}
        dump_offsets: Dict[int, dict] = {}
        if blob is not None:
            states, dump_offsets = blob
        for node, _ in self._sources:
            source = node.config["source"]
            source.restore(
                last_offsets.get(node.id, {}),
                states.get(node.id),
                dump_offsets.get(node.id, {}).get("consumed", 0),
            )

    def step(self) -> bool:
        """Run one commit; returns True if any node produced output.

        Each commit runs in two phases mirroring the reference's alt/neu timestamps
        (``dataflow.rs:3447``): the even ("alt") phase moves normal data; the odd ("neu")
        phase moves *forgetting* retractions drained from Forget/AsofNow operators. Keeping
        the phases separate guarantees a delta is never a mix of real updates and
        forgetting updates, so ``_filter_out_results_of_forgetting`` can drop whole neu
        deltas without losing genuine data.
        """
        self.current_time = self._commit * 2  # even data times, as in the reference
        self.draining = self._ready and self.sources_finished()
        any_output = self._substep(neu=False)
        if any(
            getattr(self.evaluators[n.id], "neu_pending", _no_pending)()
            for n in self._nodes
        ):
            self.current_time = self._commit * 2 + 1
            any_output = self._substep(neu=True) or any_output
        if (
            self._persistence is not None
            and self._inject is None
            and any(len(d) for d in self._input_deltas.values())
        ):
            offsets = {n.id: n.config["source"].offset_state() for n, _ in self._sources}
            self._persistence.record_commit(self._commit, self._input_deltas, offsets)
            # markers are O(1) handles to in-band subject checkpoints; dump only
            # when one actually advanced
            markers = {
                n.id: m
                for n, _ in self._sources
                if (m := n.config["source"].subject_state()) is not None
            }
            if markers and {k: id(v) for k, v in markers.items()} != self._dumped_markers:
                self._persistence.maybe_dump_sources(
                    {nid: m[0] for nid, m in markers.items()},
                    {nid: {"consumed": m[1]} for nid, m in markers.items()},
                )
                self._dumped_markers = {k: id(v) for k, v in markers.items()}
        if self._monitor is not None:
            self._monitor.update(self._commit, self._step_counts, self.states)
        self._commit += 1
        return any_output

    def _substep(self, *, neu: bool) -> bool:
        if not neu:
            self._step_counts = {}
        deltas: Dict[int, Delta] = {}
        any_output = False
        for node in self._nodes:
            evaluator = self.evaluators[node.id]
            if isinstance(node, pg.InputNode):
                if neu:
                    delta = Delta.empty(self.output_columns_of(node))
                elif self._inject is not None:
                    # journal replay: feed the persisted delta instead of the source
                    delta = self._inject.get(
                        node.id, Delta.empty(self.output_columns_of(node))
                    )
                else:
                    delta = evaluator.process([])
                if not neu:
                    self._input_deltas[node.id] = delta
            else:
                inputs = [
                    deltas.get(inp._node.id, Delta.empty(inp.column_names()))
                    for inp in node.inputs
                ]
                originates = neu and getattr(evaluator, "neu_pending", _no_pending)()
                if (
                    all(len(d) == 0 for d in inputs)
                    and not originates
                    and not (not neu and _has_pending(evaluator))
                    and node.kind != "iterate_result"
                ):
                    delta = Delta.empty(self.output_columns_of(node))
                elif originates:
                    delta = evaluator.drain_neu(inputs)
                else:
                    delta = evaluator.process(inputs)
                if neu and len(delta):
                    delta.neu = True
            deltas[node.id] = delta
            if len(delta):
                any_output = True
                self._step_counts[node.id] = self._step_counts.get(node.id, 0) + len(delta)
                if node.output is not None:
                    self.states[node.id].apply(delta)
        return any_output

    def output_columns_of(self, node: pg.Node) -> List[str]:
        return node.output.column_names() if node.output is not None else []

    def sources_finished(self) -> bool:
        return all(node.config["source"].is_finished() for node, _ in self._sources)

    def has_pending(self) -> bool:
        return any(_has_pending(e) for e in self.evaluators.values())

    def finish(self) -> None:
        from pathway_tpu.engine.evaluators import OutputEvaluator

        for node in self._nodes:
            evaluator = self.evaluators.get(node.id)
            if isinstance(evaluator, OutputEvaluator):
                evaluator.finish()
        if self._persistence is not None:
            self._persistence.close()
        if self._monitor is not None:
            self._monitor.close()

    def run(
        self,
        *,
        monitoring_level: Any = None,
        with_http_server: bool = False,
        terminate_on_error: bool = True,
        max_commits: int | None = None,
        persistence_config: Any = None,
        **kwargs: Any,
    ) -> None:
        if not self._ready:
            self.setup(monitoring_level, persistence_config=persistence_config)
        commits = 0
        try:
            while True:
                any_output = self.step()
                commits += 1
                if max_commits is not None and commits >= max_commits:
                    break
                if self.sources_finished() and not any_output and not self.has_pending():
                    break
                if not any_output and not self.sources_finished():
                    time_mod.sleep(0.001)
        finally:
            if max_commits is None:
                self.finish()


def _has_pending(evaluator: Any) -> bool:
    has = getattr(evaluator, "has_pending", None)
    return bool(has()) if has is not None else False


def _no_pending() -> bool:
    return False


def _make_monitor(level: Any, nodes: List[pg.Node]) -> Any:
    if level is None:
        return None
    from pathway_tpu.internals.monitoring import MonitoringLevel, StatsMonitor

    if level in (MonitoringLevel.NONE, "none"):
        return None
    return StatsMonitor(nodes)


def run(**kwargs: Any) -> None:
    """Execute the global dataflow graph (parity: ``pw.run``, reference ``run.py:12``)."""
    GraphRunner(pg.G).run(**kwargs)


def run_all(**kwargs: Any) -> None:
    run(**kwargs)
