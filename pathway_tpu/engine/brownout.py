"""Overload brownout ladder for the serving plane.

Below the autoscaler's scaling rung (``parallel/autoscaler.py``) sits a
cheaper defense: when the embed admission queue saturates, the serving plane
degrades GRACEFULLY — admission caps tighten and retrieval gets cheaper —
*before* a reshard pause is spent. "Shed first, scale second, recover always":
the autoscaler only escalates to a membership transition once the brownout
rungs have been engaged and load still exceeds capacity.

Rungs (driven by embed-queue occupancy, the fraction of
``max_queue_rows`` currently waiting/in flight):

====  ==================  =============================================
rung  engages at           degradation
====  ==================  =============================================
0     —                   none (normal serving)
1     occupancy >= 0.60   REST admission cap x0.5, coalesce window x0.5
2     occupancy >= 0.85   REST admission cap x0.25, coalesce window ->0,
                          IVF ``n_probe`` halved (recall traded for
                          latency — serving stays up)
====  ==================  =============================================

Rungs RELEASE with hysteresis: occupancy must stay below ~70% of the engage
threshold for ``hold_s`` seconds before a rung disengages, so a queue
oscillating around a threshold does not flap the ladder. Every engage/release
bumps ``brownout.engage``/``brownout.release`` stage counters and lands a
``brownout`` flight-recorder event, so post-mortems show the ladder's history
next to the commit timeline.

The **quiesce window** rides the same registry: while a membership transition
pauses the commit loop (``GraphRunner._run_membership_transition``), the REST
plane must serve 429 + an honest ``Retry-After`` (the expected remaining
pause) instead of letting clients hang on a paused engine —
:meth:`BrownoutState.enter_quiesce` / :meth:`~BrownoutState.exit_quiesce`
bracket the window and ``rest_connector`` consults
:meth:`~BrownoutState.quiesce_retry_after` pre-admission.

``PATHWAY_BROWNOUT=off`` disables the ladder entirely (level stays 0, the
quiesce window still sheds — a paused engine hangs clients regardless of the
ladder). Process-wide singleton via :func:`get_brownout`;
:func:`reset_brownout` rebuilds (tests).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

# (engage_occupancy, admission_scale, coalesce_window_scale, nprobe_shift)
# per rung, rung 0 implicit
_RUNGS = (
    (0.60, 0.5, 0.5, 0),
    (0.85, 0.25, 0.0, 1),
)
# occupancy must stay below engage * _RELEASE_RATIO for hold_s to disengage
_RELEASE_RATIO = 0.7


def retry_after_int(seconds: float) -> str:
    """RFC-9110 ``Retry-After`` value: a base-10 NON-NEGATIVE INTEGER of
    seconds (no float, no sign, no units). One home for every shed path —
    REST overload, quiesce, and the replica staleness shed all format
    through here, so the header stays parseable by strict clients. Rounds
    UP (a client told to wait 0.3 s that retries at 0 s hammers the very
    queue the shed protects) with a floor of 1."""
    try:
        value = float(seconds)
    except (TypeError, ValueError):
        value = 1.0
    if value != value or value < 0:  # NaN / negative: shed "momentarily"
        value = 1.0
    value = min(value, 3600.0)  # a shed is a backoff hint, not a ban
    return str(max(1, int(-(-value // 1))))


class BrownoutState:
    """Thread-safe overload-degradation ladder (see module docstring)."""

    def __init__(self, *, enabled: "bool | None" = None, hold_s: float = 1.0):
        if enabled is None:
            enabled = os.environ.get("PATHWAY_BROWNOUT", "on").lower() not in (
                "off", "0", "false", "no",
            )
        self.enabled = bool(enabled)
        self.hold_s = float(hold_s)
        self._lock = threading.Lock()
        self._level = 0
        # per-rung: the last time occupancy was ABOVE the rung's release
        # threshold (hysteresis clock; 0.0 = never)
        self._last_above = [0.0] * len(_RUNGS)
        self._engages = 0
        self._releases = 0
        # quiesce window: (entered_monotonic, expected_duration_s) while a
        # membership transition has the commit loop paused
        self._quiesce: "Optional[tuple]" = None

    # -- ladder ----------------------------------------------------------------

    def observe_occupancy(self, frac: float, now: "float | None" = None) -> int:
        """Feed one embed-queue occupancy sample (0..1+); returns the level
        after the update. Called from the admission path — cheap, one lock."""
        if not self.enabled:
            return 0
        if now is None:
            now = time.monotonic()
        frac = max(0.0, float(frac))
        events = []
        with self._lock:
            old = self._level
            for i, (engage, _adm, _win, _np) in enumerate(_RUNGS):
                if frac >= engage * _RELEASE_RATIO:
                    self._last_above[i] = now
            # engage the deepest rung whose threshold the sample crosses
            level = self._level
            for i, (engage, _adm, _win, _np) in enumerate(_RUNGS):
                if frac >= engage:
                    level = max(level, i + 1)
            # release any rung that stayed quiet for hold_s
            while level > 0:
                i = level - 1
                if (
                    frac < _RUNGS[i][0]
                    and now - self._last_above[i] >= self.hold_s
                ):
                    level -= 1
                else:
                    break
            self._level = level
            if level > old:
                self._engages += level - old
                events.append(("engage", old, level, frac))
            elif level < old:
                self._releases += old - level
                events.append(("release", old, level, frac))
        for kind, frm, to, occ in events:
            self._emit(kind, frm, to, occ)
        return self._level

    def _emit(self, kind: str, from_level: int, to_level: int, occupancy: float) -> None:
        # deferred imports: this module sits under the serving hot path and
        # must stay light at module load
        try:
            from pathway_tpu.engine import telemetry

            telemetry.stage_add(f"brownout.{kind}")
        except Exception:
            pass
        try:
            from pathway_tpu.engine.profile import get_flight_recorder

            get_flight_recorder().record_event(
                "brownout",
                action=kind,
                from_level=from_level,
                to_level=to_level,
                occupancy=round(float(occupancy), 3),
            )
        except Exception:
            pass

    def level(self) -> int:
        with self._lock:
            return self._level

    def admission_scale(self) -> float:
        """Multiplier on the REST ``max_pending`` admission cap (1.0 at
        rung 0)."""
        with self._lock:
            level = self._level
        return _RUNGS[level - 1][1] if level > 0 else 1.0

    def coalesce_window_scale(self) -> float:
        """Multiplier on the query coalescer's ``max_wait_ms`` window (a
        shorter window trades batching efficiency for latency under load)."""
        with self._lock:
            level = self._level
        return _RUNGS[level - 1][2] if level > 0 else 1.0

    def nprobe_shift(self) -> int:
        """Right-shift applied to IVF ``n_probe`` at query time (rung 2:
        half the probes — recall degrades honestly instead of the queue
        growing without bound)."""
        with self._lock:
            level = self._level
        return _RUNGS[level - 1][3] if level > 0 else 0

    # -- quiesce window (membership transition) --------------------------------

    def enter_quiesce(self, expected_s: float = 1.0) -> None:
        """A membership transition paused the commit loop: REST requests
        admitted now would hang until C+1 — shed them instead (429 with the
        expected remaining pause as Retry-After). Active regardless of the
        ladder's enable gate."""
        with self._lock:
            self._quiesce = (time.monotonic(), max(0.1, float(expected_s)))
        try:
            from pathway_tpu.engine import telemetry

            telemetry.stage_add("brownout.quiesce_enter")
        except Exception:
            pass

    def exit_quiesce(self) -> None:
        with self._lock:
            self._quiesce = None

    def quiesce_retry_after(self) -> "Optional[float]":
        """Remaining expected pause in seconds while quiesced, else None."""
        with self._lock:
            quiesce = self._quiesce
        if quiesce is None:
            return None
        entered, expected = quiesce
        return max(0.5, expected - (time.monotonic() - entered))

    # -- reporting -------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "level": self._level,
                "engages": self._engages,
                "releases": self._releases,
                "quiesced": self._quiesce is not None,
                "enabled": self.enabled,
            }


_brownout: "Optional[BrownoutState]" = None
_brownout_lock = threading.Lock()


def get_brownout() -> BrownoutState:
    """The process-wide brownout ladder (built once from the env)."""
    global _brownout
    with _brownout_lock:
        if _brownout is None:
            _brownout = BrownoutState()
        return _brownout


def reset_brownout() -> None:
    """Drop the singleton so the next :func:`get_brownout` re-reads the env."""
    global _brownout
    with _brownout_lock:
        _brownout = None
