"""Row-wise expression compilation & evaluation.

Parity with the reference's typed expression interpreter (``src/engine/expression.rs``) and the
Python-side translation layer (``internals/graph_runner/expression_evaluator.py``). This module
is the host INTERPRETER: vectorized numpy over whole column batches, ``apply`` UDFs batched at
the column level rather than row-at-a-time. The device path lives in
``pathway_tpu/engine/fusion.py``: the fusion compiler composes whole select/filter CHAINS of
these expression trees and lowers device-friendly runs to single jitted XLA programs, using
this interpreter both as the fallback and as the bitwise ground truth its parity probe checks
lowered programs against — any semantic change here must keep the two in lockstep (the probe
will catch a divergence by falling back, never by corrupting output).
"""

from __future__ import annotations

import operator
from functools import lru_cache
from typing import Any, Callable, Dict, Mapping

import numpy as np

from pathway_tpu.engine.columnar import ERROR, Error
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.keys import Pointer, pointer_from


class EvalContext:
    """Resolves column references to materialized numpy columns for one batch.

    ``diffs`` + ``memo`` enable non-deterministic-apply replay: a UDF flagged
    ``deterministic=False`` must emit the SAME value when a row retracts as it did
    when the row was inserted (reference UDF ``deterministic`` contract,
    ``internals/udfs/__init__.py``) — so insert-row results are memoized by row key
    and retraction rows replay them instead of re-invoking the UDF. This is both a
    correctness obligation (a re-invocation could differ, leaving a dangling
    retraction) and the serving-path fast path (a query's delete-completed
    retraction must not re-run the embedder)."""

    def __init__(
        self,
        n_rows: int,
        resolver: Callable[[expr.ColumnReference], np.ndarray],
        keys: np.ndarray | None = None,
        diffs: np.ndarray | None = None,
        memo: Dict[Any, dict] | None = None,
        memo_tokens: Dict[int, str] | None = None,
    ):
        self.n_rows = n_rows
        self.resolver = resolver
        self.keys = keys
        self.diffs = diffs
        self.memo = memo
        # id(expr) -> stable snapshot-safe token (see Evaluator._memo_tokens)
        self.memo_tokens = memo_tokens or {}


# Run-scoped UDF error policy, set per thread by the GraphRunner (reference
# terminate_on_error switch, graph.rs:996): when not terminating, a raising UDF poisons
# its cell with Error and reports to the error log instead of failing the run.
# Thread-local: LiveTable background runs and concurrent runners don't interfere.
import threading as _threading

_runtime_tls = _threading.local()


def get_runtime() -> Dict[str, Any]:
    rt = getattr(_runtime_tls, "rt", None)
    if rt is None:
        rt = _runtime_tls.rt = {
            "terminate_on_error": True,
            # fallback error sink for operators without a local log (set by the
            # outermost run; nested iterate runners inherit it)
            "global_source": None,
            "node": None,  # the operator Node currently evaluating
        }
    return rt


def report_udf_error(message: str) -> None:
    rt = get_runtime()
    node = rt["node"]
    source = getattr(node, "error_log_source", None) or rt["global_source"]
    if source is not None:
        frame = getattr(node, "user_frame", None)
        trace = None
        if frame is not None:
            trace = {
                "file": frame.filename,
                "line": frame.line_number,
                "function": frame.function,
            }
        source.push(node.id if node is not None else -1, message, trace)


def _call_udf(fun: Callable, args: list, kwargs: dict) -> Any:
    if get_runtime()["terminate_on_error"]:
        return fun(*args, **kwargs)
    try:
        return fun(*args, **kwargs)
    except Exception as exc:
        report_udf_error(f"{type(exc).__name__}: {exc}")
        return ERROR


def _broadcast_const(value: Any, n: int) -> np.ndarray:
    if isinstance(value, (bool, np.bool_)):
        return np.full(n, value, dtype=np.bool_)
    if isinstance(value, (int, np.integer)):
        return np.full(n, value, dtype=np.int64)
    if isinstance(value, (float, np.floating)):
        return np.full(n, value, dtype=np.float64)
    out = np.empty(n, dtype=object)
    out[:] = [value] * n
    return out


_NUMERIC_KINDS = frozenset("bif")


def _is_numeric(arr: np.ndarray) -> bool:
    return arr.dtype != object and arr.dtype.kind in _NUMERIC_KINDS


def _checked_div(op: Callable, left: np.ndarray, right: np.ndarray) -> np.ndarray:
    bad = right == 0
    if np.any(bad):
        safe = np.where(bad, 1, right)
        result = op(left, safe).astype(object)
        result[np.asarray(bad)] = ERROR
        return result
    return op(left, right)


def _object_binary(op: Callable, left: np.ndarray, right: np.ndarray) -> np.ndarray:
    """Python-semantics elementwise op with Error poisoning."""

    def wrapped(a: Any, b: Any) -> Any:
        if isinstance(a, Error) or isinstance(b, Error):
            return ERROR
        try:
            return op(a, b)
        except Exception:
            return ERROR

    return np.frompyfunc(wrapped, 2, 1)(left, right)


def _tidy(arr: np.ndarray) -> np.ndarray:
    """Collapse object arrays of uniform numeric values back to typed arrays."""
    if arr.dtype != object or len(arr) == 0:
        return arr
    first = arr[0]
    if isinstance(first, (bool, np.bool_)):
        try:
            return arr.astype(np.bool_)
        except (ValueError, TypeError):
            return arr
    if isinstance(first, (int, np.integer)) and not isinstance(first, bool):
        try:
            return arr.astype(np.int64)
        except (ValueError, TypeError, OverflowError):
            return arr
    if isinstance(first, (float, np.floating)):
        try:
            return arr.astype(np.float64)
        except (ValueError, TypeError):
            return arr
    return arr


class ExpressionEvaluator:
    """Evaluates an expression AST over a batch of rows."""

    def __init__(self, ctx: EvalContext):
        self.ctx = ctx

    def eval(self, e: expr.ColumnExpression) -> np.ndarray:
        result = self._eval(e)
        if np.isscalar(result) or (isinstance(result, np.ndarray) and result.ndim == 0):
            return _broadcast_const(result.item() if hasattr(result, "item") else result, self.ctx.n_rows)
        return result

    # -- dispatch -----------------------------------------------------------

    def _eval(self, e: expr.ColumnExpression) -> np.ndarray:
        method = getattr(self, "_eval_" + type(e).__name__, None)
        if method is None:
            raise NotImplementedError(f"cannot evaluate {type(e).__name__}")
        return method(e)

    def _eval_ColumnConstExpression(self, e: expr.ColumnConstExpression) -> np.ndarray:
        return _broadcast_const(e._value, self.ctx.n_rows)

    def _eval_ColumnReference(self, e: expr.ColumnReference) -> np.ndarray:
        return self.ctx.resolver(e)

    def _eval_ColumnBinaryOpExpression(self, e: expr.ColumnBinaryOpExpression) -> np.ndarray:
        left = self._eval(e._left)
        right = self._eval(e._right)
        op = e._operator
        if _is_numeric(left) and _is_numeric(right):
            if op in (operator.truediv, operator.floordiv, operator.mod):
                return _checked_div(op, left, right)
            if op is operator.pow and left.dtype.kind == "i" and np.any(right < 0):
                return op(left.astype(np.float64), right)
            if op in (operator.and_, operator.or_, operator.xor) and (
                left.dtype == np.bool_ or right.dtype == np.bool_
            ):
                return op(left.astype(np.bool_), right.astype(np.bool_))
            return op(left, right)
        # datetime arithmetic stays in numpy datetime64/timedelta64
        if left.dtype != object and right.dtype != object:
            try:
                return op(left, right)
            except TypeError:
                pass
        return _tidy(_object_binary(op, left, right))

    def _eval_ColumnUnaryOpExpression(self, e: expr.ColumnUnaryOpExpression) -> np.ndarray:
        val = self._eval(e._expr)
        op = e._operator
        if _is_numeric(val):
            if op is operator.not_:
                return ~val.astype(np.bool_)
            return op(val)
        def wrapped(a: Any) -> Any:
            if isinstance(a, Error):
                return ERROR
            try:
                return op(a)
            except Exception:
                return ERROR
        return _tidy(np.frompyfunc(wrapped, 1, 1)(val))

    def _eval_IfElseExpression(self, e: expr.IfElseExpression) -> np.ndarray:
        cond = self._eval(e._if)
        then = self._eval(e._then)
        otherwise = self._eval(e._else)
        if cond.dtype == object:
            err = np.frompyfunc(lambda v: isinstance(v, Error), 1, 1)(cond).astype(bool)
            safe = np.where(err, False, cond)
            cond = safe.astype(np.bool_)
            if err.any():
                # poisoned condition poisons the output cell (Value::Error contract)
                out = np.empty(self.ctx.n_rows, dtype=object)
                out[cond] = then[cond]
                out[~cond] = otherwise[~cond]
                out[err] = ERROR
                return out
        if then.dtype == object or otherwise.dtype == object:
            out = np.empty(self.ctx.n_rows, dtype=object)
            out[cond] = then[cond]
            out[~cond] = otherwise[~cond]
            return _tidy(out)
        if then.dtype != otherwise.dtype:
            common = np.promote_types(then.dtype, otherwise.dtype)
            then = then.astype(common)
            otherwise = otherwise.astype(common)
        return np.where(cond, then, otherwise)

    def _eval_CoalesceExpression(self, e: expr.CoalesceExpression) -> np.ndarray:
        args = [self._eval(a) for a in e._args]
        out = np.empty(self.ctx.n_rows, dtype=object)
        out[:] = None
        filled = np.zeros(self.ctx.n_rows, dtype=bool)
        for arr in args:
            if arr.dtype == object:
                present = np.frompyfunc(lambda v: v is not None, 1, 1)(arr).astype(bool)
            else:
                present = np.ones(self.ctx.n_rows, dtype=bool)
            take = present & ~filled
            out[take] = arr[take]
            filled |= present
            if filled.all():
                break
        return _tidy(out)

    def _eval_RequireExpression(self, e: expr.RequireExpression) -> np.ndarray:
        val = self._eval(e._val)
        out = val.astype(object) if val.dtype != object else val.copy()
        for arg in e._args:
            arr = self._eval(arg)
            if arr.dtype == object:
                missing = np.frompyfunc(lambda v: v is None, 1, 1)(arr).astype(bool)
                out[missing] = None
        return _tidy(out)

    def _eval_IsNoneExpression(self, e: expr.IsNoneExpression) -> np.ndarray:
        val = self._eval(e._expr)
        if val.dtype != object:
            return np.zeros(self.ctx.n_rows, dtype=np.bool_)
        return np.frompyfunc(lambda v: v is None, 1, 1)(val).astype(np.bool_)

    def _eval_IsNotNoneExpression(self, e: expr.IsNotNoneExpression) -> np.ndarray:
        return ~self._eval_IsNoneExpression(expr.IsNoneExpression(e._expr))

    def _eval_CastExpression(self, e: expr.CastExpression) -> np.ndarray:
        return self._convert(self._eval(e._expr), e._target, strict=False)

    def _eval_ConvertExpression(self, e: expr.ConvertExpression) -> np.ndarray:
        val = self._eval(e._expr)
        default = self._eval(e._default)
        out = self._convert(val, e._target, strict=False, default=default)
        return out

    def _eval_DeclareTypeExpression(self, e: expr.DeclareTypeExpression) -> np.ndarray:
        return self._eval(e._expr)

    def _eval_UnwrapExpression(self, e: expr.UnwrapExpression) -> np.ndarray:
        val = self._eval(e._expr)
        if val.dtype == object:
            has_none = np.frompyfunc(lambda v: v is None, 1, 1)(val).astype(bool)
            if np.any(has_none):
                raise ValueError("unwrap() applied to a None value")
            return _tidy(val)
        return val

    def _eval_FillErrorExpression(self, e: expr.FillErrorExpression) -> np.ndarray:
        val = self._eval(e._expr)
        repl = self._eval(e._replacement)
        if val.dtype != object:
            return val
        is_err = np.frompyfunc(lambda v: isinstance(v, Error), 1, 1)(val).astype(bool)
        if not np.any(is_err):
            return val
        out = val.copy()
        out[is_err] = repl[is_err]
        return _tidy(out)

    def _convert(
        self,
        val: np.ndarray,
        target: dt.DType,
        strict: bool,
        default: np.ndarray | None = None,
    ) -> np.ndarray:
        def conv(v: Any, d: Any = None) -> Any:
            if isinstance(v, Error):
                return ERROR
            if v is None:
                return d
            try:
                if isinstance(v, Json):
                    v = v.value
                    if v is None:
                        return d
                if target == dt.INT:
                    return int(v)
                if target == dt.FLOAT:
                    return float(v)
                if target == dt.BOOL:
                    if isinstance(v, (bool, np.bool_)):
                        return bool(v)
                    raise ValueError(f"cannot convert {v!r} to bool")
                if target == dt.STR:
                    return str(v)
                return v
            except (ValueError, TypeError):
                return ERROR

        if default is not None:
            out = np.frompyfunc(conv, 2, 1)(val, default)
        else:
            out = np.frompyfunc(lambda v: conv(v, None), 1, 1)(val)
        return _tidy(out)

    _MEMO_MISS = object()

    def _memo_store(self, e: expr.ApplyExpression) -> "dict | None":
        """The per-expression replay store for a non-deterministic apply, when the
        calling evaluator supplied keys/diffs/memo (see EvalContext docstring)."""
        ctx = self.ctx
        if (
            getattr(e, "_deterministic", True)
            or ctx.keys is None
            or ctx.diffs is None
            or ctx.memo is None
        ):
            return None
        return ctx.memo.setdefault(ctx.memo_tokens.get(id(e), id(e)), {})

    def _memo_replay(self, store: "dict | None", out: np.ndarray) -> np.ndarray:
        """Fill retraction rows from the store; returns the replayed-row mask."""
        replayed = np.zeros(self.ctx.n_rows, dtype=bool)
        if store:
            from pathway_tpu.internals.keys import key_bytes

            neg = np.nonzero(self.ctx.diffs < 0)[0]
            if len(neg):
                for i, kb in zip(neg, key_bytes(self.ctx.keys[neg])):
                    v = store.pop(kb, self._MEMO_MISS)
                    if v is not self._MEMO_MISS:
                        out[i] = v
                        replayed[i] = True
        return replayed

    def _memo_record(self, store: "dict | None", out: np.ndarray) -> None:
        if store is None:
            return
        from pathway_tpu.internals.keys import key_bytes

        pos = np.nonzero(self.ctx.diffs > 0)[0]
        if len(pos):
            for i, kb in zip(pos, key_bytes(self.ctx.keys[pos])):
                store[kb] = out[i]

    def _eval_ApplyExpression(self, e: expr.ApplyExpression) -> np.ndarray:
        args = [self._eval(a) for a in e._args]
        kwargs = {k: self._eval(v) for k, v in e._kwargs.items()}
        out = np.empty(self.ctx.n_rows, dtype=object)
        store = self._memo_store(e)
        replayed = self._memo_replay(store, out)
        for i in range(self.ctx.n_rows):
            if replayed[i]:
                continue
            row_args = [a[i] for a in args]
            row_kwargs = {k: v[i] for k, v in kwargs.items()}
            if e._propagate_none and (
                any(a is None for a in row_args) or any(v is None for v in row_kwargs.values())
            ):
                out[i] = None
                continue
            if any(isinstance(a, Error) for a in row_args) or any(
                isinstance(v, Error) for v in row_kwargs.values()
            ):
                out[i] = ERROR
                continue
            out[i] = _call_udf(e._fun, row_args, row_kwargs)
        self._memo_record(store, out)
        return _tidy(out) if e._return_type != dt.ANY else out

    def _eval_BatchApplyExpression(self, e: expr.ApplyExpression) -> np.ndarray:
        args = [self._eval(a) for a in e._args]
        kwargs = {k: self._eval(v) for k, v in e._kwargs.items()}
        max_bs = e._max_batch_size or self.ctx.n_rows or 1
        out = np.empty(self.ctx.n_rows, dtype=object)
        store = self._memo_store(e)
        replayed = self._memo_replay(store, out)
        # poisoned rows never reach the UDF; their outputs stay ERROR
        poisoned = np.zeros(self.ctx.n_rows, dtype=bool)
        for col in args + list(kwargs.values()):
            if col.dtype == object:
                poisoned |= np.frompyfunc(lambda v: isinstance(v, Error), 1, 1)(col).astype(
                    bool
                )
        poisoned &= ~replayed
        clean_idx = np.nonzero(~poisoned & ~replayed)[0]
        out[poisoned] = ERROR
        # batch-level stage accounting (engine/telemetry.py stage counters): one
        # timing add per COMMIT batch, so the serving/ingest hot paths stay
        # observable (embed time vs engine time) at negligible cost
        import time as _time

        _t0 = _time.perf_counter()
        for start in range(0, len(clean_idx), max_bs):
            idx = clean_idx[start : start + max_bs]
            batch_args = [list(a[idx]) for a in args]
            batch_kwargs = {k: list(v[idx]) for k, v in kwargs.items()}
            results = _call_udf(e._fun, batch_args, batch_kwargs)
            if isinstance(results, Error):
                for i in idx:
                    out[i] = ERROR
                continue
            results = list(results)
            if len(results) != len(idx):
                raise ValueError(
                    f"batch UDF returned {len(results)} results for a batch of {len(idx)} rows"
                )
            for i, r in zip(idx, results):
                out[i] = r
        if len(clean_idx):
            from pathway_tpu.engine import telemetry as _telemetry

            _telemetry.stage_add("eval.batch_udf_s", _time.perf_counter() - _t0)
            _telemetry.stage_add("eval.batch_udf_rows", float(len(clean_idx)))
        self._memo_record(store, out)
        return out

    def _eval_AsyncApplyExpression(self, e: expr.AsyncApplyExpression) -> np.ndarray:
        import asyncio

        args = [self._eval(a) for a in e._args]
        kwargs = {k: self._eval(v) for k, v in e._kwargs.items()}
        out = np.empty(self.ctx.n_rows, dtype=object)
        store = self._memo_store(e)
        replayed = self._memo_replay(store, out)
        run_rows = np.nonzero(~replayed)[0]

        async def run_all() -> list:
            tasks = [
                e._fun(*[a[i] for a in args], **{k: v[i] for k, v in kwargs.items()})
                for i in run_rows
            ]
            return await asyncio.gather(*tasks, return_exceptions=True)

        results = _run_coro(run_all())
        terminate = get_runtime()["terminate_on_error"]
        for i, r in zip(run_rows, results):
            if isinstance(r, Exception):
                if terminate:
                    raise r
                report_udf_error(f"{type(r).__name__}: {r}")
                out[i] = ERROR
            else:
                out[i] = r
        self._memo_record(store, out)
        return _tidy(out)

    _eval_FullyAsyncApplyExpression = _eval_AsyncApplyExpression

    def _eval_PointerExpression(self, e: expr.PointerExpression) -> np.ndarray:
        args = [self._eval(a) for a in e._args]
        if e._instance is not None:
            args.append(self._eval(e._instance))
        out = np.empty(self.ctx.n_rows, dtype=object)
        for i in range(self.ctx.n_rows):
            out[i] = pointer_from(*[a[i] for a in args])
        return out

    def _eval_MakeTupleExpression(self, e: expr.MakeTupleExpression) -> np.ndarray:
        args = [self._eval(a) for a in e._args]
        out = np.empty(self.ctx.n_rows, dtype=object)
        for i in range(self.ctx.n_rows):
            out[i] = tuple(a[i] for a in args)
        return out

    def _eval_GetExpression(self, e: expr.GetExpression) -> np.ndarray:
        obj = self._eval(e._object)
        index = self._eval(e._index)
        default = self._eval(e._default)
        out = np.empty(self.ctx.n_rows, dtype=object)
        for i in range(self.ctx.n_rows):
            o, idx = obj[i], index[i]
            try:
                if isinstance(o, Json):
                    v = o.value[idx]
                    out[i] = Json(v) if isinstance(v, (dict, list)) else v
                else:
                    out[i] = o[idx]
            except (KeyError, IndexError, TypeError) as exc:
                if e._check_if_exists:
                    out[i] = default[i]
                elif get_runtime()["terminate_on_error"]:
                    # checked [] access: a missing index fails the run unless
                    # error poisoning was opted into (reference get_checked).
                    # Keep the original exception type — a KeyError on a Json
                    # dict must not read as a sequence-bounds problem
                    raise type(exc)(
                        f"cannot index {o!r} with {idx!r}"
                    ) from exc
                else:
                    out[i] = ERROR
        return _tidy(out)

    def _eval_MethodCallExpression(self, e: expr.MethodCallExpression) -> np.ndarray:
        args = [self._eval(a) for a in e._args]
        return e._fun(*args)


def _run_coro(coro: Any) -> Any:
    import asyncio

    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        loop = None
    if loop is None:
        return asyncio.run(coro)
    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(max_workers=1) as pool:
        return pool.submit(asyncio.run, coro).result()


def evaluate(
    e: expr.ColumnExpression,
    n_rows: int,
    resolver: Callable[[expr.ColumnReference], np.ndarray],
    keys: np.ndarray | None = None,
    diffs: np.ndarray | None = None,
    memo: "Dict[Any, dict] | None" = None,
    memo_tokens: "Dict[int, str] | None" = None,
) -> np.ndarray:
    return ExpressionEvaluator(
        EvalContext(n_rows, resolver, keys, diffs, memo, memo_tokens)
    ).eval(e)
