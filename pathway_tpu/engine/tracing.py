"""Distributed tracing plane: causal spans across REST, encoder, mesh, replicas.

The PR-5 metrics plane answers "how much / how slow" per rank; this module
answers "why was THIS query slow". Every hop of a request — REST admission,
the coalescer/encoder tick that batched it, the commit that served it, the
exchange barrier it waited behind, the replica that answered — records a
:class:`Span` carrying (trace_id, span_id, parent_id, rank, kind, wall +
monotonic stamps, attrs, links), and the per-rank rings merge offline into one
causally-ordered tree with a critical path.

Design points, in the order they matter:

- **Head sampling with deterministic consistency.** The sampling decision is a
  pure function of the trace id (``_head_sampled``): every rank and component
  derives the SAME decision without exchanging a bit, which is what keeps a
  commit's spans consistent across ranks (the commit trace id itself is a pure
  function of ``(epoch, commit)`` — lockstep commit numbers are the cross-rank
  trace key, no wire change required). An explicit ``X-Pathway-Trace`` flag
  overrides the hash for that trace (callers can force-sample a request).
- **Slow promotion.** Unsampled traces buffer in a bounded pending map; when a
  trace's ROOT span finishes over ``PATHWAY_TRACE_SLOW_MS`` the whole local
  buffer promotes into the ring (``trace.promoted``), otherwise it drops when
  the root closes. Promotion is per-rank local by construction — a slow commit
  is slow on every rank that waited behind its barrier, so in practice all
  ranks promote the same trace.
- **Zero hot-path operator spans.** ``GraphRunner`` does NOT wrap operators in
  spans; per-operator / fused-region child spans are synthesized from the
  already-collected :class:`~pathway_tpu.engine.profile.CommitProfile` ops at
  commit end, and only for sampled/promoted commits. The <2% telemetry
  overhead contract (``bench.py telemetry``) stays honest.
- **Crash-safe flush.** The ring flushes to ``trace-rank-N.jsonl`` on finish
  AND alongside every flight-recorder dump (crash, fence, SIGTERM, chaos
  kill) via :func:`pathway_tpu.engine.profile.register_trace_hooks` — a
  killed rank still leaves a partial trace. The lock is an RLock for the same
  reason the flight recorder's is: dumps run from signal handlers that may
  have interrupted a holder on the same thread.

The ring/flush lifecycle and the trace-context handoff across a membership
transition are model-checked (``internals/protocol_models.trace_ring_model``):
no span orphaned by an epoch bump, flush-on-crash never deadlocks the dying
rank, sampling decision consistent across a trace.

Env knobs: ``PATHWAY_TRACE=off`` disables span recording (header echo stays);
``PATHWAY_TRACE_SAMPLE`` is the head-sampling probability (default 0.01);
``PATHWAY_TRACE_SLOW_MS`` always-samples roots slower than this (default 250);
``PATHWAY_TRACE_RING`` sizes the span ring (default 4096);
``PATHWAY_TRACE_DIR`` overrides the flush directory (default: the flight
recorder's dump dir).
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from pathway_tpu.engine import telemetry

#: REST trace-propagation header (in AND out on every route). Value format:
#: ``<trace_id 16hex>-<span_id 16hex>-<flags 2hex>`` (flags bit 0 = sampled),
#: a deliberately W3C-traceparent-shaped shape without the version field.
TRACE_HEADER = "X-Pathway-Trace"

_ID_HEX = 16  # 64-bit ids, rendered as 16 hex chars

# pending (unsampled, promotion-eligible) buffer bounds: per-trace and total
_MAX_PENDING_TRACES = 64
_MAX_PENDING_SPANS = 128
# bounded link registries (query-text -> ctx, admitted-query ctx feed)
_MAX_LINK_KEYS = 256
_MAX_LINKS_PER_KEY = 32


def _new_id() -> str:
    return os.urandom(_ID_HEX // 2).hex()


def _derived_id(seed: str) -> str:
    return hashlib.sha1(seed.encode("utf-8")).hexdigest()[:_ID_HEX]


class TraceContext:
    """The propagating identity of a span: enough to parent a child anywhere
    (another thread, another rank, another process) and to keep the sampling
    decision consistent along the way."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"TraceContext({self.trace_id}, {self.span_id}, sampled={self.sampled})"


class Span:
    """One timed unit of work. ``ts`` is wall-clock (cross-rank merge, after
    clock-offset correction), ``ts_mono`` is monotonic (intra-rank ordering
    immune to wall-clock steps); both stamp at START, ``duration_s`` closes."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "rank", "epoch", "kind", "name",
        "ts", "ts_mono", "duration_s", "attrs", "links", "sampled", "root",
    )

    def __init__(
        self,
        *,
        trace_id: str,
        span_id: str,
        parent_id: Optional[str],
        rank: int,
        epoch: int,
        kind: str,
        name: str,
        sampled: bool,
        root: bool,
        links: Tuple[TraceContext, ...] = (),
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.rank = rank
        self.epoch = epoch
        self.kind = kind
        self.name = name
        self.ts = time.time()
        self.ts_mono = time.monotonic()
        self.duration_s = 0.0
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.links: List[Dict[str, str]] = [
            {"trace_id": l.trace_id, "span_id": l.span_id} for l in links
        ]
        self.sampled = sampled
        self.root = root

    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id, self.sampled)

    def add_link(self, ctx: TraceContext) -> None:
        self.links.append({"trace_id": ctx.trace_id, "span_id": ctx.span_id})

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "rank": self.rank,
            "epoch": self.epoch,
            "kind": self.kind,
            "name": self.name,
            "ts": self.ts,
            "ts_mono": self.ts_mono,
            "duration_s": self.duration_s,
            "attrs": self.attrs,
            "links": self.links,
        }


# -- context propagation helpers ---------------------------------------------


def parse_trace_header(value: Optional[str]) -> Optional[TraceContext]:
    """Parse an ``X-Pathway-Trace`` value; tolerant — malformed input is
    treated as absent (a bad client header must not 500 the route)."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) < 2:
        return None
    trace_id, span_id = parts[0].lower(), parts[1].lower()
    if len(trace_id) != _ID_HEX or len(span_id) != _ID_HEX:
        return None
    try:
        int(trace_id, 16)
        int(span_id, 16)
    except ValueError:
        return None
    if len(parts) >= 3 and parts[2] in ("00", "01"):
        sampled = parts[2] == "01"  # explicit flag overrides the hash
    else:
        sampled = _head_sampled(trace_id)
    return TraceContext(trace_id, span_id, sampled)


def format_trace_header(ctx: TraceContext) -> str:
    return f"{ctx.trace_id}-{ctx.span_id}-{'01' if ctx.sampled else '00'}"


def _head_sampled(trace_id: str) -> bool:
    """THE sampling decision: a pure function of the trace id, so every rank
    and component agrees without exchanging a bit."""
    rate = get_tracer().sample_rate
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return (int(trace_id[:8], 16) / float(1 << 32)) < rate


def new_trace_context(sampled: Optional[bool] = None) -> TraceContext:
    trace_id = _new_id()
    return TraceContext(
        trace_id,
        _new_id(),
        _head_sampled(trace_id) if sampled is None else sampled,
    )


def commit_trace_context(epoch: int, commit: int, rank: int = 0) -> TraceContext:
    """Deterministic identity for commit ``commit`` of mesh epoch ``epoch``:
    every rank derives the same trace id (lockstep commit numbers are the
    cross-rank key — nothing rides the wire) and its own span id, so all
    ranks' commit spans are siblings in one trace."""
    trace_id = _derived_id(f"commit:{epoch}:{commit}")
    span_id = _derived_id(f"{trace_id}:rank:{rank}")
    return TraceContext(trace_id, span_id, _head_sampled(trace_id))


_current_span: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "pathway_trace_span", default=None
)


def current_context() -> Optional[TraceContext]:
    span = _current_span.get()
    return span.context() if span is not None else None


# -- the tracer ---------------------------------------------------------------


class Tracer:
    """Bounded per-rank span ring + pending (promotion-eligible) buffers +
    link registries. One RLock: flush may run from a signal handler that
    interrupted a holder on the same thread (see module docstring)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self.enabled = True
        self.sample_rate = 0.01
        self.slow_ms = 250.0
        self.rank = 0
        self.epoch = 0
        self._default_dir: Optional[str] = None
        self._ring: "collections.deque[Span]" = collections.deque(maxlen=4096)
        # trace_id -> finished-but-unsampled spans awaiting the root's verdict
        self._pending: "collections.OrderedDict[str, List[Span]]" = (
            collections.OrderedDict()
        )
        # query-text key -> contexts of REST spans waiting on that text
        # (drained by the encoder tick that batches the text)
        self._query_links: "collections.OrderedDict[str, List[TraceContext]]" = (
            collections.OrderedDict()
        )
        # contexts admitted since the last commit (drained by the commit span)
        self._commit_links: List[TraceContext] = []
        self._offsets: Dict[int, float] = {}
        self.flushes = 0
        self.refresh()

    # -- configuration --------------------------------------------------------

    def refresh(self) -> None:
        """Re-read the env knobs (tests flip them between runs)."""
        env = os.environ
        # opt-in master gate (README: default off) — unset must mean OFF, or
        # every engine in the process pays span bookkeeping nobody asked for
        enabled = env.get("PATHWAY_TRACE", "").lower() in (
            "1", "true", "yes", "on",
        )
        rate = 0.01
        try:
            rate = float(env.get("PATHWAY_TRACE_SAMPLE", "0.01"))
        except ValueError:
            pass
        slow_ms = 250.0
        try:
            slow_ms = float(env.get("PATHWAY_TRACE_SLOW_MS", "250"))
        except ValueError:
            pass
        ring = 4096
        try:
            ring = max(64, int(env.get("PATHWAY_TRACE_RING", "4096")))
        except ValueError:
            pass
        with self._lock:
            self.enabled = enabled
            self.sample_rate = min(1.0, max(0.0, rate))
            self.slow_ms = max(0.0, slow_ms)
            if self._ring.maxlen != ring:
                self._ring = collections.deque(self._ring, maxlen=ring)

    def configure(
        self, *, rank: Optional[int] = None, default_dir: Optional[str] = None
    ) -> None:
        with self._lock:
            if rank is not None:
                self.rank = rank
            if default_dir is not None:
                self._default_dir = default_dir
        self.refresh()

    def set_epoch(self, epoch: int) -> None:
        """Membership transition: spans opened after this stamp the new epoch.
        Pending buffers survive the bump — a span recorded under the old epoch
        is never orphaned by the transition (model invariant)."""
        with self._lock:
            self.epoch = epoch

    def set_clock_offsets(self, offsets: Dict[int, float]) -> None:
        """Heartbeat-estimated ``peer_wall - local_wall`` seconds per peer
        (the merger aligns rank files with these; see ``cluster.py``)."""
        with self._lock:
            self._offsets = dict(offsets)

    # -- span lifecycle -------------------------------------------------------

    def start(
        self,
        kind: str,
        name: Optional[str] = None,
        *,
        ctx: Optional[TraceContext] = None,
        self_ctx: Optional[TraceContext] = None,
        links: Tuple[TraceContext, ...] = (),
        attrs: Optional[Dict[str, Any]] = None,
    ) -> Optional[Span]:
        """Open a span. ``ctx`` parents it explicitly (falls back to the
        context-local current span); ``self_ctx`` instead assigns the span's
        OWN identity (deterministic commit spans). Returns None when tracing
        is off — callers must tolerate that."""
        if not self.enabled:
            return None
        parent = ctx if ctx is not None else current_context()
        if self_ctx is not None:
            span = Span(
                trace_id=self_ctx.trace_id,
                span_id=self_ctx.span_id,
                parent_id=parent.span_id if parent is not None else None,
                rank=self.rank,
                epoch=self.epoch,
                kind=kind,
                name=name or kind,
                sampled=self_ctx.sampled,
                root=parent is None,
                links=links,
                attrs=attrs,
            )
        elif parent is not None:
            span = Span(
                trace_id=parent.trace_id,
                span_id=_new_id(),
                parent_id=parent.span_id,
                rank=self.rank,
                epoch=self.epoch,
                kind=kind,
                name=name or kind,
                sampled=parent.sampled,
                root=False,
                links=links,
                attrs=attrs,
            )
        else:
            root_ctx = new_trace_context()
            span = Span(
                trace_id=root_ctx.trace_id,
                span_id=root_ctx.span_id,
                parent_id=None,
                rank=self.rank,
                epoch=self.epoch,
                kind=kind,
                name=name or kind,
                sampled=root_ctx.sampled,
                root=True,
                links=links,
                attrs=attrs,
            )
        return span

    def finish(self, span: Span) -> None:
        """Close a span and route it: sampled -> ring; unsampled -> pending
        until its trace's root closes (slow root promotes the buffer, fast
        root drops it)."""
        if span.duration_s == 0.0:
            span.duration_s = max(0.0, time.monotonic() - span.ts_mono)
        slow = span.duration_s * 1000.0 >= self.slow_ms
        with self._lock:
            if span.sampled:
                self._ring.append(span)
                telemetry.stage_add("trace.span")
                return
            if span.root and slow:
                # always-sample slow roots: promote the whole local buffer
                span.sampled = True
                promoted = self._pending.pop(span.trace_id, [])
                for buffered in promoted:
                    buffered.sampled = True
                    self._ring.append(buffered)
                self._ring.append(span)
                telemetry.stage_add_many({
                    "trace.span": float(len(promoted) + 1),
                    "trace.promoted": 1.0,
                })
                return
            if span.root:
                dropped = self._pending.pop(span.trace_id, None)
                if dropped:
                    telemetry.stage_add("trace.dropped", float(len(dropped)))
                return
            bucket = self._pending.get(span.trace_id)
            if bucket is None:
                while len(self._pending) >= _MAX_PENDING_TRACES:
                    _, evicted = self._pending.popitem(last=False)
                    telemetry.stage_add("trace.dropped", float(len(evicted)))
                bucket = self._pending[span.trace_id] = []
            if len(bucket) < _MAX_PENDING_SPANS:
                bucket.append(span)

    @contextlib.contextmanager
    def trace_span(
        self,
        kind: str,
        name: Optional[str] = None,
        *,
        ctx: Optional[TraceContext] = None,
        self_ctx: Optional[TraceContext] = None,
        links: Tuple[TraceContext, ...] = (),
        attrs: Optional[Dict[str, Any]] = None,
    ) -> Iterator[Optional[Span]]:
        """The one span-recording API (PWA205 lints literal ``kind`` args
        against ``telemetry.TRACE_SPAN_KINDS``). Yields the open span (or None
        when tracing is off) and installs it as the context-local parent."""
        span = self.start(
            kind, name, ctx=ctx, self_ctx=self_ctx, links=links, attrs=attrs
        )
        if span is None:
            yield None
            return
        token = _current_span.set(span)
        try:
            yield span
        finally:
            _current_span.reset(token)
            self.finish(span)

    def record_span(
        self,
        kind: str,
        name: str,
        *,
        parent: TraceContext,
        ts: float,
        ts_mono: float,
        duration_s: float,
        attrs: Optional[Dict[str, Any]] = None,
        links: Tuple[TraceContext, ...] = (),
    ) -> None:
        """Synthesize an already-finished child span (operator / fused-region
        rows lifted from a CommitProfile at commit end — nothing on the
        operator hot path). Only call for sampled/promoted parents."""
        if not self.enabled:
            return
        span = Span(
            trace_id=parent.trace_id,
            span_id=_new_id(),
            parent_id=parent.span_id,
            rank=self.rank,
            epoch=self.epoch,
            kind=kind,
            name=name,
            sampled=True,
            root=False,
            links=links,
            attrs=attrs,
        )
        span.ts = ts
        span.ts_mono = ts_mono
        span.duration_s = duration_s
        with self._lock:
            self._ring.append(span)
            telemetry.stage_add("trace.span")

    # -- link registries ------------------------------------------------------

    def register_query_link(self, key: str, ctx: TraceContext) -> None:
        """A REST query span waiting on ``key`` (the query text): the encoder
        tick that batches the text drains these into its span's links."""
        if not self.enabled:
            return
        with self._lock:
            bucket = self._query_links.get(key)
            if bucket is None:
                while len(self._query_links) >= _MAX_LINK_KEYS:
                    self._query_links.popitem(last=False)
                bucket = self._query_links[key] = []
            if len(bucket) < _MAX_LINKS_PER_KEY:
                bucket.append(ctx)

    def take_query_links(self, keys: List[str]) -> List[TraceContext]:
        if not self.enabled:
            return []
        out: List[TraceContext] = []
        with self._lock:
            for key in keys:
                out.extend(self._query_links.pop(key, ()))
        return out

    def register_commit_link(self, ctx: TraceContext) -> None:
        """A query admitted since the last commit: the next commit span links
        it (a query racing the boundary links the adjacent commit)."""
        if not self.enabled:
            return
        with self._lock:
            if len(self._commit_links) < _MAX_LINKS_PER_KEY:
                self._commit_links.append(ctx)

    def take_commit_links(self) -> List[TraceContext]:
        if not self.enabled:
            return []
        with self._lock:
            out, self._commit_links = self._commit_links, []
        return out

    # -- flush / dump ---------------------------------------------------------

    def recent_spans(self, limit: int = 128) -> List[Dict[str, Any]]:
        """Snapshot of the newest ring spans (flight-dump embedding): safe to
        call from a signal handler — the RLock is reentrant and the snapshot
        is read-only."""
        with self._lock:
            spans = list(self._ring)[-limit:]
        return [s.to_dict() for s in spans]

    def _resolve_dir(self) -> Optional[str]:
        return os.environ.get("PATHWAY_TRACE_DIR") or self._default_dir

    def flush_path(self, directory: Optional[str] = None) -> Optional[str]:
        directory = directory or self._resolve_dir()
        if directory is None:
            return None
        return os.path.join(directory, f"trace-rank-{self.rank}.jsonl")

    def flush(
        self, directory: Optional[str] = None, reason: str = "flush"
    ) -> Optional[str]:
        """Write the ring to ``trace-rank-N.jsonl`` (atomic rename; first
        record is ``_meta`` with the clock offsets the merger aligns by).
        Never raises — a failing flush must not mask the failure being
        recorded."""
        if not self.enabled:
            return None
        path = self.flush_path(directory)
        if path is None:
            return None
        with self._lock:
            spans = [s.to_dict() for s in self._ring]
            meta = {
                "_meta": {
                    "rank": self.rank,
                    "epoch": self.epoch,
                    "reason": reason,
                    "ts": time.time(),
                    "ts_mono": time.monotonic(),
                    "clock_offsets": {str(k): v for k, v in self._offsets.items()},
                }
            }
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(json.dumps(meta))
                f.write("\n")
                for span in spans:
                    f.write(json.dumps(span))
                    f.write("\n")
            os.replace(tmp, path)
            with self._lock:
                self.flushes += 1
            telemetry.stage_add("trace.flush")
            return path
        except (OSError, TypeError, ValueError):
            return None

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._pending.clear()
            self._query_links.clear()
            self._commit_links = []
            self._offsets = {}
            self.flushes = 0
        self.refresh()


_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    """Process-wide tracer (lazily built from the env)."""
    global _tracer
    inst = _tracer  # noqa: PWA103 (double-checked locking: unlocked fast-path read; the only WRITE is under _tracer_lock below)
    if inst is None:
        with _tracer_lock:
            inst = _tracer
            if inst is None:
                inst = _tracer = Tracer()
                _register_flight_hooks(inst)
    return inst


def trace_span(
    kind: str,
    name: Optional[str] = None,
    *,
    ctx: Optional[TraceContext] = None,
    self_ctx: Optional[TraceContext] = None,
    links: Tuple[TraceContext, ...] = (),
    attrs: Optional[Dict[str, Any]] = None,
) -> "contextlib.AbstractContextManager[Optional[Span]]":
    """Module-level convenience over :meth:`Tracer.trace_span`."""
    return get_tracer().trace_span(
        kind, name, ctx=ctx, self_ctx=self_ctx, links=links, attrs=attrs
    )


def reset_tracing() -> None:
    """Test/bench hook: clear the ring, buffers, and registries (the tracer
    keeps its rank/dir config, re-reads the env knobs)."""
    inst = _tracer  # noqa: PWA103 (read-only peek at the singleton; writes stay under _tracer_lock in get_tracer)
    if inst is not None:
        inst.reset()


def _register_flight_hooks(tracer: Tracer) -> None:
    """Ride the flight recorder's dump paths: every crash/fence/chaos dump
    embeds the newest spans in its payload AND flushes the jsonl next to it,
    so a killed rank still yields a partial trace."""
    from pathway_tpu.engine import profile

    def _spans() -> Dict[str, Any]:
        return {"rank": tracer.rank, "spans": tracer.recent_spans()}

    def _flush(directory: Optional[str], reason: str) -> None:
        tracer.flush(directory, reason=reason)

    profile.register_trace_hooks(_spans, _flush)


# -- merging + critical path --------------------------------------------------


def load_trace_file(path: str) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """Read one ``trace-rank-N.jsonl``: ``(meta, spans)``; tolerant of torn
    tails (a rank killed mid-write loses at most its last line)."""
    meta: Dict[str, Any] = {}
    spans: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except ValueError:
                continue  # torn tail
            if "_meta" in record:
                meta = record["_meta"]
            else:
                spans.append(record)
    return meta, spans


def load_flight_spans(path: str) -> List[Dict[str, Any]]:
    """Spans embedded in a flight dump (``flight-rank-N.json``) — the partial
    trace a chaos-killed rank left behind."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return []
    trace = payload.get("trace") or {}
    spans = trace.get("spans") or []
    return [s for s in spans if isinstance(s, dict) and "span_id" in s]


def merge_trace_files(
    paths: List[str], flight_paths: Optional[List[str]] = None
) -> Dict[str, Any]:
    """Join per-rank trace files (plus flight-dump partials) into one span
    set, wall clocks aligned to rank 0's frame via the heartbeat-estimated
    offsets each rank recorded in its ``_meta``."""
    metas: Dict[int, Dict[str, Any]] = {}
    spans: List[Dict[str, Any]] = []
    seen: set = set()
    for path in paths:
        try:
            meta, file_spans = load_trace_file(path)
        except OSError:
            continue
        rank = int(meta.get("rank", -1))
        if rank >= 0:
            metas[rank] = meta
        for span in file_spans:
            key = (span.get("span_id"), span.get("rank"))
            if key not in seen:
                seen.add(key)
                spans.append(span)
    for path in flight_paths or []:
        for span in load_flight_spans(path):
            key = (span.get("span_id"), span.get("rank"))
            if key not in seen:
                seen.add(key)
                spans.append(span)
    # offsets[r] estimates rank-r wall minus rank-0 wall: prefer rank 0's own
    # measurement of peer r; fall back to rank r's measurement of peer 0
    offsets: Dict[int, float] = {0: 0.0}
    zero_meta = metas.get(0, {})
    zero_offsets = zero_meta.get("clock_offsets", {})
    for rank, meta in metas.items():
        if rank == 0:
            continue
        if str(rank) in zero_offsets:
            offsets[rank] = float(zero_offsets[str(rank)])
        elif "0" in meta.get("clock_offsets", {}):
            offsets[rank] = -float(meta["clock_offsets"]["0"])
        else:
            offsets[rank] = 0.0
    for span in spans:
        span["ts_adj"] = float(span.get("ts", 0.0)) - offsets.get(
            int(span.get("rank", 0)), 0.0
        )
    spans.sort(key=lambda s: s["ts_adj"])
    return {"spans": spans, "offsets": offsets, "ranks": sorted(metas)}


def _trace_tree(
    spans: List[Dict[str, Any]], trace_id: str
) -> Tuple[List[Dict[str, Any]], Dict[str, List[Dict[str, Any]]]]:
    """(roots, children-by-parent) for one trace, children in causal order."""
    mine = [s for s in spans if s.get("trace_id") == trace_id]
    ids = {s["span_id"] for s in mine}
    children: Dict[str, List[Dict[str, Any]]] = {}
    roots: List[Dict[str, Any]] = []
    for span in mine:
        parent = span.get("parent_id")
        if parent and parent in ids:
            children.setdefault(parent, []).append(span)
        else:
            roots.append(span)
    for bucket in children.values():
        bucket.sort(key=lambda s: s.get("ts_adj", s.get("ts", 0.0)))
    roots.sort(key=lambda s: s.get("ts_adj", s.get("ts", 0.0)))
    return roots, children


def critical_path(
    merged: Dict[str, Any], trace_id: Optional[str] = None
) -> Optional[Dict[str, Any]]:
    """The trace's critical path: from the slowest root, follow the
    largest-duration child to a leaf. Returns ``{"trace_id", "root", "path",
    "line"}`` — ``line`` is the post-mortem one-liner ("commit 4812: 78% in
    rank 1 groupby; barrier held 41 ms by rank 3")."""
    spans = merged.get("spans", [])
    if trace_id is None:
        best: Optional[Dict[str, Any]] = None
        for span in spans:
            if span.get("parent_id") is None and (
                best is None or span["duration_s"] > best["duration_s"]
            ):
                best = span
        if best is None:
            return None
        trace_id = best["trace_id"]
    roots, children = _trace_tree(spans, trace_id)
    if not roots:
        return None
    root = max(roots, key=lambda s: s.get("duration_s", 0.0))
    path = [root]
    node = root
    while True:
        kids = children.get(node["span_id"], [])
        if not kids:
            break
        node = max(kids, key=lambda s: s.get("duration_s", 0.0))
        path.append(node)
    leaf = path[-1]
    root_dur = max(root.get("duration_s", 0.0), 1e-9)
    pct = 100.0 * leaf.get("duration_s", 0.0) / root_dur
    line = (
        f"{root['name']}: {pct:.0f}% in rank {leaf.get('rank', '?')} "
        f"{leaf['name']}"
    )
    slowest_barrier: Optional[Dict[str, Any]] = None
    for span in spans:
        if span.get("trace_id") != trace_id or span.get("kind") != "barrier":
            continue
        wait = float(span.get("attrs", {}).get("straggler_wait_s", 0.0))
        if wait > 0.0 and (
            slowest_barrier is None
            or wait > float(slowest_barrier["attrs"]["straggler_wait_s"])
        ):
            slowest_barrier = span
    if slowest_barrier is not None:
        attrs = slowest_barrier["attrs"]
        line += (
            f"; barrier held {float(attrs['straggler_wait_s']) * 1000.0:.0f} ms "
            f"by rank {attrs.get('straggler_rank', '?')}"
        )
    return {"trace_id": trace_id, "root": root, "path": path, "line": line}


def format_trace_tree(merged: Dict[str, Any], trace_id: str) -> List[str]:
    """Indented causally-ordered rendering of one trace (``cli trace``)."""
    spans = merged.get("spans", [])
    roots, children = _trace_tree(spans, trace_id)
    lines: List[str] = []

    def _walk(span: Dict[str, Any], depth: int) -> None:
        link_note = ""
        if span.get("links"):
            link_note = f" links={len(span['links'])}"
        lines.append(
            f"{'  ' * depth}{span['kind']} {span['name']} "
            f"[rank {span.get('rank', '?')}] "
            f"{span.get('duration_s', 0.0) * 1000.0:.2f} ms{link_note}"
        )
        for child in children.get(span["span_id"], []):
            _walk(child, depth + 1)

    for root in roots:
        _walk(root, 0)
    return lines


def critical_path_line(directory: str) -> Optional[str]:
    """Convenience for the supervisor's post-mortem: merge whatever trace
    files (and flight-dump partials) the dir holds and return the critical
    path one-liner, or None when there is nothing to say."""
    import glob as _glob

    paths = sorted(_glob.glob(os.path.join(directory, "trace-rank-*.jsonl")))
    flights = sorted(_glob.glob(os.path.join(directory, "flight-rank-*.json")))
    if not paths and not flights:
        return None
    merged = merge_trace_files(paths, flights)
    if not merged["spans"]:
        return None
    result = critical_path(merged)
    return result["line"] if result else None
