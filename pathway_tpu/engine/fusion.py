"""Whole-commit fusion: compile operator chains into single programs.

The execution half of the fusion compiler (planning lives in
``pathway_tpu/analysis/fusion.py``). A :class:`ChainProgram` executes one
maximal run of consecutive ``rowwise``/``filter`` nodes as a single unit
instead of one evaluator dispatch per node:

- **composed evaluation** — the chain's column environment flows node to node
  with no intermediate ``Delta`` objects, no per-node state-table traffic, and
  dead-column elimination (an interior column nothing downstream reads is
  never computed, provided its expression is pure — see ``PURE_EXPRS``);
- **XLA lowering** — a run of map steps whose expressions are built from
  device-friendly scalar ops lowers to ONE jitted JAX program; shapes are
  padded to pow2 buckets (``internals/shapes.py``) so ragged commit sizes hit
  a bounded jit cache, and the padded operand buffers are donated so XLA may
  write outputs in place;
- **bitwise honesty** — the first batch through every lowered program is ALSO
  evaluated by the stock interpreter and compared byte-for-byte (dtypes
  included). Any deviation (e.g. FMA contraction on float chains — XLA:CPU
  contracts ``a*b+c`` where numpy rounds twice) permanently downgrades that
  program to the interpreter and bumps ``fuse.jit_parity_rejects``. Fused
  output is bit-identical to unfused BY CONSTRUCTION, not by hope.

Stateful members of a fused region (join/groupby/concat) keep executing
through their own incremental evaluators — their arrangements ARE the carried
state, held across commits rather than re-materialized per substep — while the
chains around them fuse. Counters ride ``engine/telemetry.py`` under
``fuse.*``; the region plan is logged as a ``fusion`` flight-recorder event.

Env knobs: ``PATHWAY_FUSION=off|on`` (runner gate, default on);
``PATHWAY_FUSION_JIT_ROWS`` — minimum batch rows before a lowered program
dispatches to XLA (default 32768; below it the interpreter wins on host);
``PATHWAY_FUSION_JIT=0`` — disable the XLA path, keep composed evaluation.
"""

from __future__ import annotations

import operator
import os
import time as time_mod
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from pathway_tpu.analysis.fusion import ChainSpec, expr_pure
from pathway_tpu.engine import expression_evaluator as ee
from pathway_tpu.engine import telemetry
from pathway_tpu.engine.columnar import Delta
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals.shapes import next_pow2

# operators that lower 1:1 onto jnp arrays through their dunder dispatch and
# are bit-exact per op (integer ops exact; float add/sub/mul/cmp exact PER OP —
# cross-op contraction is what the parity probe exists to catch)
_LOWER_OPS: Set[Any] = {
    operator.add, operator.sub, operator.mul,
    operator.gt, operator.lt, operator.ge, operator.le,
    operator.eq, operator.ne,
    operator.and_, operator.or_, operator.xor,
    operator.lshift, operator.rshift,
}
# division family lowers only with a CONSTANT nonzero right operand: the
# interpreter's zero-divisor path poisons cells with host Error objects,
# which no device program can reproduce
_DIV_OPS: Set[Any] = {operator.truediv, operator.floordiv, operator.mod}
_LOWER_UNARY: Set[Any] = {operator.neg, operator.not_}

_JIT_FLOOR = 8  # minimum pow2 pad bucket (lane alignment; shared convention)


def _jit_threshold() -> int:
    try:
        return max(1, int(os.environ.get("PATHWAY_FUSION_JIT_ROWS", str(1 << 15))))
    except ValueError:
        return 1 << 15


def _jit_enabled() -> bool:
    return os.environ.get("PATHWAY_FUSION_JIT", "").lower() not in (
        "0", "false", "no", "off",
    )


def _lowerable(e: expr.ColumnExpression) -> bool:
    """True when the whole tree maps onto the jnp op whitelist (static check;
    runtime dtypes are verified per batch, and the parity probe has the final
    word)."""
    if isinstance(e, expr.ColumnConstExpression):
        v = e._value
        return isinstance(v, (bool, int, float, np.bool_, np.integer, np.floating))
    if isinstance(e, expr.ColumnReference):
        return e.name != "id"  # key pointers are host objects
    if isinstance(e, expr.ColumnBinaryOpExpression):
        op = e._operator
        if op in _DIV_OPS:
            right = e._right
            if not (
                isinstance(right, expr.ColumnConstExpression)
                and isinstance(right._value, (int, float, np.integer, np.floating))
                and not isinstance(right._value, bool)
                and right._value != 0
            ):
                return False
            return _lowerable(e._left)
        return op in _LOWER_OPS and _lowerable(e._left) and _lowerable(e._right)
    if isinstance(e, expr.ColumnUnaryOpExpression):
        return e._operator in _LOWER_UNARY and _lowerable(e._expr)
    if isinstance(e, expr.IfElseExpression):
        return _lowerable(e._if) and _lowerable(e._then) and _lowerable(e._else)
    return False


def _expr_ref_names(e: expr.ColumnExpression) -> Set[str]:
    return {ref.name for ref in e._column_refs}


def _to_host_view(out: Any, rows: int) -> np.ndarray:
    """Host ndarray over a program output, zero-copy where the backend allows.

    On the CPU backend the XLA output buffer IS host memory: ``np.from_dlpack``
    wraps it without the ~1 ms/MB copy ``np.asarray`` pays per column. The
    returned view keeps the producing buffer alive (dlpack capsule ref), and
    deltas are immutable once emitted, so sharing is safe. Any failure (older
    jax, non-CPU backend layouts) falls back to the copying path."""
    try:
        arr = np.from_dlpack(out)
    except Exception:
        arr = np.asarray(out)
    return arr[:rows]


class _RunStep:
    """One map node inside a lowered run, split into *computed* columns (these
    lower to XLA) and *aliases* — bare column renames/pass-throughs, which stay
    host-side array references exactly like the interpreter's resolver returns
    them (a string key threading through an arithmetic chain must neither
    block lowering nor round-trip through the device)."""

    __slots__ = ("node", "compute", "aliases")

    def __init__(self, node: pg.Node, live: List[str]):
        self.node = node
        self.compute: Dict[str, expr.ColumnExpression] = {}
        self.aliases: Dict[str, str] = {}
        exprs = node.config["exprs"]
        for name in live:
            e = exprs[name]
            if isinstance(e, expr.ColumnReference) and e.name != "id":
                self.aliases[name] = e.name
            else:
                self.compute[name] = e


class _LoweredRun:
    """One maximal run of consecutive map steps (plus, optionally, the mask of
    the filter immediately after) lowered to a single jitted XLA program.

    ``steps`` is a list of :class:`_RunStep` — every *computed* expression
    statically lowerable; aliases propagate host-side. ``outputs`` lists the
    externally visible computed columns as ``(step_index, name)``; the mask,
    when present, rides as one extra output. The jit cache is keyed by the
    pow2 row bucket; input buffers are fresh pad copies owned by this run, so
    they are donated where the backend supports it (XLA may write outputs
    into the input storage instead of allocating)."""

    def __init__(
        self,
        steps: List[_RunStep],
        in_names: List[str],
        outputs: List[Tuple[int, str]],
        mask_node: "pg.Node | None",
    ):
        self.steps = steps
        self.in_names = in_names
        self.outputs = outputs
        self.mask_node = mask_node
        self._fns: Dict[int, Any] = {}  # pow2 bucket -> jitted fn
        self.compiles = 0
        # pow2 buckets whose compiled program passed the first-batch bitwise
        # parity check. Verification is PER BUCKET, matching the compile
        # granularity: each bucket is a distinct XLA program and the backend
        # may make different codegen choices per shape (a verified 64k-bucket
        # program says nothing about the 256k one).
        self.verified: Set[int] = set()
        self.disabled = not _jit_enabled()
        self.hits = 0

    @property
    def mask_expr(self) -> "expr.ColumnExpression | None":
        return None if self.mask_node is None else self.mask_node.config["expression"]

    # -- tracing --------------------------------------------------------------

    def _lower_expr(self, e: Any, env: Dict[str, Any], n: int, jnp: Any) -> Any:
        if isinstance(e, expr.ColumnConstExpression):
            v = e._value
            if isinstance(v, (bool, np.bool_)):
                return jnp.full((n,), bool(v), dtype=np.bool_)
            if isinstance(v, (int, np.integer)):
                return jnp.full((n,), int(v), dtype=np.int64)
            return jnp.full((n,), float(v), dtype=np.float64)
        if isinstance(e, expr.ColumnReference):
            return env[e.name]
        if isinstance(e, expr.ColumnBinaryOpExpression):
            left = self._lower_expr(e._left, env, n, jnp)
            right = self._lower_expr(e._right, env, n, jnp)
            op = e._operator
            # mirror ExpressionEvaluator._eval_ColumnBinaryOpExpression's
            # numeric path: bool coercion for the bitwise trio, nothing else
            if op in (operator.and_, operator.or_, operator.xor) and (
                left.dtype == np.bool_ or right.dtype == np.bool_
            ):
                return op(left.astype(np.bool_), right.astype(np.bool_))
            return op(left, right)
        if isinstance(e, expr.ColumnUnaryOpExpression):
            val = self._lower_expr(e._expr, env, n, jnp)
            if e._operator is operator.not_:
                return ~val.astype(np.bool_)
            return e._operator(val)
        if isinstance(e, expr.IfElseExpression):
            cond = self._lower_expr(e._if, env, n, jnp)
            then = self._lower_expr(e._then, env, n, jnp)
            other = self._lower_expr(e._else, env, n, jnp)
            if then.dtype != other.dtype:
                common = np.promote_types(then.dtype, other.dtype)
                then = then.astype(common)
                other = other.astype(common)
            return jnp.where(cond, then, other)
        raise NotImplementedError(type(e).__name__)

    def _fn_for(self, bucket: int) -> Any:
        fn = self._fns.get(bucket)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        def traced(*arrays: Any) -> tuple:
            env = dict(zip(self.in_names, arrays))
            step_envs: List[Dict[str, Any]] = []
            for step in self.steps:
                new_env = {
                    out: env[src] for out, src in step.aliases.items() if src in env
                }
                for name, e in step.compute.items():
                    new_env[name] = self._lower_expr(e, env, bucket, jnp)
                env = new_env
                step_envs.append(env)
            outs = [step_envs[idx][name] for idx, name in self.outputs]
            if self.mask_node is not None:
                outs.append(self._lower_expr(self.mask_expr, env, bucket, jnp))
            return tuple(outs)

        # padded operand buffers are fresh copies owned by the caller: donate
        # them so XLA may write outputs into the input storage. The CPU
        # backend does not implement donation (warns and copies) — donate only
        # where it is real.
        donate: tuple = ()
        if jax.default_backend() != "cpu":
            donate = tuple(range(len(self.in_names)))
        fn = jax.jit(traced, donate_argnums=donate)
        self._fns[bucket] = fn
        self.compiles += 1
        telemetry.stage_add("fuse.jit_compiles")
        return fn

    # -- dispatch -------------------------------------------------------------

    def run_device(
        self, env: Dict[str, np.ndarray], rows: int
    ) -> "Optional[Tuple[Dict[Tuple[int, str], np.ndarray], Optional[np.ndarray], int]]":
        """Execute on device; returns ``(outputs by (step, name), mask,
        bucket)`` or None when ineligible (dtypes, import/compile failure) —
        the caller falls back to the interpreter."""
        if self.disabled:
            return None
        arrays = []
        for name in self.in_names:
            col = env[name]
            if col.dtype == object or col.dtype.kind not in "bif":
                telemetry.stage_add("fuse.jit_dtype_fallbacks")
                return None
            arrays.append(col)
        try:
            import jax  # noqa: F401
            from jax.experimental import enable_x64
        except Exception:
            self.disabled = True
            return None
        bucket = next_pow2(rows, _JIT_FLOOR)
        padded = []
        for col in arrays:
            # empty + explicit tail zero: one pass over the buffer instead of
            # zeros-then-overwrite (the pad region only feeds pad outputs,
            # which are sliced away; zeroing keeps it deterministic anyway)
            buf = np.empty(bucket, dtype=col.dtype)
            buf[:rows] = col
            buf[rows:] = 0
            padded.append(buf)
        try:
            with enable_x64():
                fn = self._fn_for(bucket)
                outs = fn(*padded)
        except Exception:
            # any tracing/compile/runtime failure: interpreter takes over for
            # the rest of this run's lifetime — never the commit's
            self.disabled = True
            telemetry.stage_add("fuse.jit_errors")
            return None
        self.hits += 1
        telemetry.stage_add("fuse.jit_hits")
        host = [_to_host_view(o, rows) for o in outs]
        mask: "Optional[np.ndarray]" = None
        if self.mask_node is not None:
            mask = host.pop().astype(bool)
        return dict(zip(self.outputs, host)), mask, bucket


class ChainProgram:
    """Executable form of one planned :class:`ChainSpec`.

    Per commit, the program pulls the head's input delta from the substep's
    ``deltas`` dict, streams the column environment through its steps (maps
    compose; filters compact eagerly so error-poisoning/row-set semantics stay
    identical to per-node dispatch), and materializes real ``Delta`` objects
    only for *exported* nodes — nodes some consumer outside the chain (or the
    state/undo machinery) actually observes. Bookkeeping (step counts, state
    application, undo capture, profiler attribution) mirrors
    ``GraphRunner._substep`` exactly — the bitwise-parity contract is with the
    per-node dispatch path, commit by commit."""

    def __init__(self, runner: Any, spec: ChainSpec, consumers: Dict[int, List[pg.Node]]):
        node_by_id = {n.id: n for n in runner._nodes}
        self.spec = spec
        self.nodes: List[pg.Node] = [node_by_id[nid] for nid in spec.node_ids]
        self.input_id = spec.input_id
        self._input_table = self.nodes[0].inputs[0]
        chain_ids = set(spec.node_ids)
        self.name = f"fuse:{self.nodes[0].name}+{len(self.nodes) - 1}"

        # exported = observable outside the fused program: an outside consumer
        # reads deltas[id], or the node's state table is materialized (state
        # application must happen delta-by-delta for checkpoint/undo parity)
        self.export: Dict[int, bool] = {}
        for i, node in enumerate(self.nodes):
            outside = any(c.id not in chain_ids for c in consumers.get(node.id, []))
            self.export[node.id] = (
                outside or node.id in runner._materialized or i == len(self.nodes) - 1
            )

        # live-column analysis, back to front: an exported node needs every
        # output column; an interior node needs the columns the next step
        # references, plus any non-pure column (whose evaluation could raise —
        # skipping it would be observable on error paths)
        self.live: Dict[int, List[str]] = {}
        needed_next: Set[str] = set()
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            all_cols = runner.output_columns_of(node)
            if node.kind == "filter":
                live = list(all_cols) if self.export[node.id] else [
                    c for c in all_cols if c in needed_next
                ]
                self.live[node.id] = live
                needed_next = set(live) | _expr_ref_names(node.config["expression"])
            else:
                exprs = node.config["exprs"]
                if self.export[node.id]:
                    live = list(all_cols)
                else:
                    live = [
                        c
                        for c in all_cols
                        if c in needed_next or not expr_pure(exprs[c])
                    ]
                self.live[node.id] = live
                needed_next = set()
                for c in live:
                    needed_next |= _expr_ref_names(exprs[c])

        self._build_runs()
        telemetry.stage_add_many({
            "fuse.chains_built": 1.0,
            "fuse.ops_fused": float(len(self.nodes)),
        })

    # -- jit run construction -------------------------------------------------

    def _build_runs(self) -> None:
        """Group consecutive lowerable map steps (optionally capped by the next
        filter's mask) into lowered runs. A run is lowered atomically: every
        live column of every step must be statically lowerable, else the run
        ends there (earlier lowerable steps still form a run; the rest stays
        on the interpreter — composed, just not on device)."""
        self.runs: Dict[int, _LoweredRun] = {}  # start step index -> run
        i = 0
        n_nodes = len(self.nodes)
        while i < n_nodes:
            node = self.nodes[i]
            if node.kind == "filter":
                if _lowerable(node.config["expression"]):
                    run = self._make_run(i, i - 1, mask_idx=i)  # mask-only
                    if run is not None:
                        self.runs[i] = run
                i += 1
                continue
            if not all(
                _lowerable(node.config["exprs"][c]) for c in self.live[node.id]
            ):
                i += 1
                continue
            j = i
            while (
                j + 1 < n_nodes
                and self.nodes[j + 1].kind == "rowwise"
                and all(
                    _lowerable(self.nodes[j + 1].config["exprs"][c])
                    for c in self.live[self.nodes[j + 1].id]
                )
            ):
                j += 1
            mask_idx = None
            if (
                j + 1 < n_nodes
                and self.nodes[j + 1].kind == "filter"
                and _lowerable(self.nodes[j + 1].config["expression"])
            ):
                mask_idx = j + 1
            run = self._make_run(i, j, mask_idx=mask_idx)
            if run is not None:
                self.runs[i] = run
            i = j + 1 if mask_idx is None else j + 2

    def _make_run(
        self, start: int, end: int, mask_idx: "int | None"
    ) -> "Optional[_LoweredRun]":
        steps: List[_RunStep] = []
        in_names: Set[str] = set()
        outputs: List[Tuple[int, str]] = []
        # origin[name] = the run-INPUT column a name aliases back to, or None
        # for computed values: a compute expression referencing an alias chain
        # pulls the underlying input column into the traced program's operands.
        # The run's input level is the PREVIOUS chain node's output (the chain
        # input only for a run starting at the head).
        if start == 0:
            base_cols = self._input_table.column_names()
        else:
            prev = self.nodes[start - 1]
            base_cols = prev.output.column_names() if prev.output is not None else []
        origin: Dict[str, "str | None"] = {c: c for c in base_cols}

        def need_refs(e: expr.ColumnExpression) -> None:
            for name in _expr_ref_names(e):
                src = origin.get(name)
                if src is not None:
                    in_names.add(src)

        for k in range(start, end + 1):
            step = _RunStep(self.nodes[k], self.live[self.nodes[k].id])
            for e in step.compute.values():
                need_refs(e)
            new_origin: Dict[str, "str | None"] = {
                out: origin.get(src) for out, src in step.aliases.items()
            }
            for name in step.compute:
                new_origin[name] = None
            origin = new_origin
            steps.append(step)
            # run outputs, for steps whose env the host observes (the last
            # step, and exported mid-run nodes whose full deltas must
            # materialize): every live column that does NOT alias back to a
            # run input — computed columns and aliases of computed columns
            # ride the device; input-origin aliases propagate host-side as
            # the same array references the interpreter would return
            if k == end or self.export[step.node.id]:
                outputs.extend(
                    (k - start, c)
                    for c in self.live[step.node.id]
                    if origin.get(c) is None
                )
        mask_node = self.nodes[mask_idx] if mask_idx is not None else None
        if mask_node is not None:
            need_refs(mask_node.config["expression"])
        if not in_names:
            return None  # constant-only program: not worth a device dispatch
        if not outputs and mask_node is None:
            return None
        return _LoweredRun(steps, sorted(in_names), outputs, mask_node)

    # -- interpreter building blocks (exact per-node parity) ------------------

    def _interp_exprs(
        self,
        node: pg.Node,
        exprs: Dict[str, expr.ColumnExpression],
        keys: np.ndarray,
        env: Dict[str, np.ndarray],
        rows: int,
        runtime: Dict[str, Any],
    ) -> Dict[str, np.ndarray]:
        from pathway_tpu.engine.evaluators import id_pointer_column

        runtime["node"] = node
        id_cache: List[Any] = []

        def resolver(ref: expr.ColumnReference) -> np.ndarray:
            if ref.name == "id":
                if not id_cache:
                    id_cache.append(id_pointer_column(keys))
                return id_cache[0]
            return env[ref.name]

        try:
            return {name: ee.evaluate(e, rows, resolver) for name, e in exprs.items()}
        except Exception as exc:
            from pathway_tpu.internals.trace import add_error_context

            raise add_error_context(exc, node) from exc

    def _mask_of(
        self,
        node: pg.Node,
        keys: np.ndarray,
        env: Dict[str, np.ndarray],
        rows: int,
        runtime: Dict[str, Any],
    ) -> np.ndarray:
        from pathway_tpu.engine.evaluators import filter_mask_to_bool

        mask = self._interp_exprs(
            node, {"__mask__": node.config["expression"]}, keys, env, rows, runtime
        )["__mask__"]
        # the SHARED coercion rule (poisoned predicate cells drop the row):
        # bitwise lockstep with FilterEvaluator by construction
        return filter_mask_to_bool(mask)

    def _probe_parity(
        self,
        run: _LoweredRun,
        keys: np.ndarray,
        env: Dict[str, np.ndarray],
        rows: int,
        runtime: Dict[str, Any],
        out_map: Dict[Tuple[int, str], np.ndarray],
        mask: "Optional[np.ndarray]",
        bucket: int,
    ) -> bool:
        """First-batch honesty check, PER POW2 BUCKET (each bucket is its own
        compiled program): interpreter vs device, byte-for-byte and
        dtype-for-dtype. A reject permanently downgrades the whole run — one
        divergent bucket means the lowering cannot be trusted."""
        ref_env = dict(env)
        step_envs: List[Dict[str, np.ndarray]] = []
        for step in run.steps:
            exprs = {
                c: step.node.config["exprs"][c] for c in self.live[step.node.id]
            }
            ref_env = self._interp_exprs(step.node, exprs, keys, ref_env, rows, runtime)
            step_envs.append(ref_env)
        ok = True
        for (idx, name), got in out_map.items():
            want = step_envs[idx][name]
            if got.dtype != want.dtype or got.tobytes() != want.tobytes():
                ok = False
                break
        if ok and mask is not None:
            want_mask = self._mask_of(
                run.mask_node, keys, step_envs[-1] if step_envs else env, rows, runtime
            )
            if mask.tobytes() != want_mask.tobytes():
                ok = False
        if not ok:
            run.disabled = True
            telemetry.stage_add("fuse.jit_parity_rejects")
            return False
        run.verified.add(bucket)
        telemetry.stage_add("fuse.jit_parity_verified")
        return True

    # -- execution ------------------------------------------------------------

    def execute(
        self,
        runner: Any,
        deltas: Dict[int, Delta],
        neu: bool,
        profile_ops: "List[tuple] | None",
        runtime: Dict[str, Any],
    ) -> bool:
        t0 = time_mod.perf_counter() if profile_ops is not None else 0.0
        self._profiling = profile_ops is not None
        in_delta = deltas.get(
            self.input_id, Delta.empty(self._input_table.column_names())
        )
        rows = len(in_delta)
        rowcounts: List[Tuple[pg.Node, int, int]] = []  # (node, rows, retractions)
        if rows == 0:
            # per-node dispatch would skip every chain node (empty input, no
            # pending state, no cluster barrier) and emit Delta.empty
            for node in self.nodes:
                if self.export[node.id]:
                    deltas[node.id] = Delta.empty(runner.output_columns_of(node))
            self._profile(profile_ops, t0, rowcounts, neu)
            return False
        threshold = _jit_threshold()
        keys, diffs = in_delta.keys, in_delta.diffs
        env: Dict[str, np.ndarray] = dict(in_delta.columns)
        any_output = False
        i = 0
        n_nodes = len(self.nodes)
        while i < n_nodes:
            node = self.nodes[i]
            if rows == 0:
                # a filter dropped everything: downstream chain nodes see empty
                # inputs and skip, exactly like per-node dispatch
                if self.export[node.id]:
                    deltas[node.id] = Delta.empty(runner.output_columns_of(node))
                i += 1
                continue
            run = self.runs.get(i)
            device_mask: "Optional[np.ndarray]" = None
            if run is not None and rows >= threshold and not run.disabled:
                got = run.run_device(env, rows)
                if got is not None and got[2] not in run.verified:
                    if not self._probe_parity(
                        run, keys, env, rows, runtime, got[0], got[1], got[2]
                    ):
                        got = None  # parity reject: interpreter from here on
                if got is not None:
                    out_map, device_mask, _bucket = got
                    for k, step in enumerate(run.steps):
                        # host-side env: alias propagation (same array refs the
                        # interpreter's resolver would return) + device outputs
                        new_env = {
                            out: env[src]
                            for out, src in step.aliases.items()
                            if src in env
                        }
                        for (kk, name), arr in out_map.items():
                            if kk == k:
                                new_env[name] = arr
                        env = new_env
                        self._after_map(
                            step.node, keys, diffs, env, rows, deltas, runner,
                            neu, rowcounts,
                        )
                        any_output = True
                    i += len(run.steps)
                    if device_mask is None:
                        continue
                    node = self.nodes[i]  # the filter the mask belongs to
            if node.kind == "rowwise":
                exprs = {c: node.config["exprs"][c] for c in self.live[node.id]}
                env = self._interp_exprs(node, exprs, keys, env, rows, runtime)
                self._after_map(
                    node, keys, diffs, env, rows, deltas, runner, neu, rowcounts
                )
                any_output = True
                i += 1
                continue
            # filter
            mask = (
                device_mask
                if device_mask is not None
                else self._mask_of(node, keys, env, rows, runtime)
            )
            keys = keys[mask]
            diffs = diffs[mask]
            env = {c: env[c][mask] for c in self.live[node.id]}
            rows = len(keys)
            if self.export[node.id]:
                delta = Delta(keys, diffs, dict(env))
                delta.neu = in_delta.neu
                if neu and rows:
                    delta.neu = True
                self._book(node, delta, deltas, runner, rowcounts)
            elif rows:
                runner._step_counts[node.id] = (
                    runner._step_counts.get(node.id, 0) + rows
                )
                rowcounts.append((node, rows, self._retr(diffs)))
            if rows:
                any_output = True
            i += 1
        self._profile(profile_ops, t0, rowcounts, neu)
        return any_output

    # -- bookkeeping (mirrors GraphRunner._substep per-node accounting) -------

    def _retr(self, diffs: np.ndarray) -> int:
        return int(np.count_nonzero(diffs < 0)) if self._profiling else 0

    def _after_map(
        self,
        node: pg.Node,
        keys: np.ndarray,
        diffs: np.ndarray,
        env: Dict[str, np.ndarray],
        rows: int,
        deltas: Dict[int, Delta],
        runner: Any,
        neu: bool,
        rowcounts: List[tuple],
    ) -> None:
        if self.export[node.id]:
            delta = Delta(
                keys, diffs, {c: env[c] for c in runner.output_columns_of(node)}
            )
            if neu and rows:
                delta.neu = True
            self._book(node, delta, deltas, runner, rowcounts)
        elif rows:
            runner._step_counts[node.id] = runner._step_counts.get(node.id, 0) + rows
            rowcounts.append((node, rows, self._retr(diffs)))

    def _book(
        self,
        node: pg.Node,
        delta: Delta,
        deltas: Dict[int, Delta],
        runner: Any,
        rowcounts: List[tuple],
    ) -> None:
        if (
            runner._undo_current is not None
            and node.id not in runner._undo_current["evals"]
        ):
            runner._capture_undo_state(node, runner.evaluators[node.id])
        deltas[node.id] = delta
        n = len(delta)
        if not n:
            return
        runner._step_counts[node.id] = runner._step_counts.get(node.id, 0) + n
        rowcounts.append((node, n, self._retr(delta.diffs)))
        if node.output is not None and node.id in runner._materialized:
            if runner._undo_current is not None:
                runner._undo_current["applied"].append((node.id, delta))
            runner.states[node.id].apply(delta)

    def _profile(
        self,
        profile_ops: "List[tuple] | None",
        t0: float,
        rowcounts: List[tuple],
        neu: bool,
    ) -> None:
        """Region row + per-member estimates (PR-5 metrics plane): the region's
        wall time is real; member seconds are attributed proportionally to
        their output rows so the ``/metrics`` operator families stay live."""
        if profile_ops is None:
            return
        elapsed = time_mod.perf_counter() - t0
        total_rows = sum(r for _n, r, _ret in rowcounts)
        head = self.nodes[0]
        profile_ops.append(
            (head.id, self.name, "fused_chain", elapsed, total_rows,
             sum(ret for _n, _r, ret in rowcounts), neu)
        )
        counted = {n.id: (r, ret) for n, r, ret in rowcounts}
        for node in self.nodes:
            r, ret = counted.get(node.id, (0, 0))
            est = (
                elapsed * (r / total_rows) if total_rows else elapsed / len(self.nodes)
            )
            profile_ops.append((node.id, node.name, node.kind, est, r, ret, neu))

    # -- introspection --------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "nodes": [n.id for n in self.nodes],
            "runs": len(self.runs),
            "jit_compiles": sum(r.compiles for r in self.runs.values()),
            "jit_buckets": sorted({b for r in self.runs.values() for b in r._fns}),
            "jit_hits": sum(r.hits for r in self.runs.values()),
            "jit_verified": sum(1 for r in self.runs.values() if r.verified),
            "jit_disabled": sum(1 for r in self.runs.values() if r.disabled),
        }


def build_schedule(runner: Any, plan: Any) -> "Optional[List[Any]]":
    """Turn a :class:`FusionPlan` into the runner's substep schedule: the node
    list with every planned chain collapsed into a :class:`ChainProgram` at the
    position of its first member. Returns None when nothing fuses (the runner
    then keeps the stock loop — zero new code on that path)."""
    if not plan.chains:
        return None
    consumers: Dict[int, List[pg.Node]] = {}
    for node in runner._nodes:
        for table in node.inputs:
            consumers.setdefault(table._node.id, []).append(node)
    head_of: Dict[int, ChainSpec] = {c.node_ids[0]: c for c in plan.chains}
    in_chain: Set[int] = {nid for c in plan.chains for nid in c.node_ids}
    schedule: List[Any] = []
    for node in runner._nodes:
        spec = head_of.get(node.id)
        if spec is not None:
            schedule.append(ChainProgram(runner, spec, consumers))
        elif node.id not in in_chain:
            schedule.append(node)
    telemetry.stage_add_many({
        "fuse.regions": float(len(plan.regions)),
        "fuse.schedules_built": 1.0,
    })
    return schedule
