"""Per-process monitoring HTTP endpoint.

Parity: reference ``src/engine/http_server.rs`` — an OpenMetrics ``/status`` endpoint on
``PATHWAY_MONITORING_HTTP_PORT`` (default 20000) + process_id, exposing input/output
latencies and row counters (``metrics_from_stats``, ``:25``).
"""

from __future__ import annotations

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

DEFAULT_MONITORING_HTTP_PORT = 20000


def _escape_label(value: str) -> str:
    """OpenMetrics label-value escaping (backslash, quote, newline)."""
    return (
        str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _format_value(value: float) -> str:
    """Ints render bare; floats keep full precision via repr."""
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def metrics_plane_lines() -> "list[str]":
    """The process-wide half of the /metrics exposition: every stage counter
    as a ``stage``-labeled counter family, per-operator totals, and every
    registered log-bucketed histogram. Shared by the worker's
    :meth:`ProberStats.to_openmetrics` and the replica serving endpoint
    (``parallel/replica.py``) so both surfaces pass the same strict-grammar
    tests — the renderer has ONE home. Returns lines WITHOUT the ``# EOF``
    terminator (callers append their own run-level families first)."""
    from pathway_tpu.engine import profile as _profile
    from pathway_tpu.engine import telemetry as _telemetry

    lines: "list[str]" = []
    stages = _telemetry.stage_snapshot()
    if stages:
        lines.append(
            "# HELP pathway_stage Cumulative in-process stage counters "
            "(keys ending _s are seconds)"
        )
        lines.append("# TYPE pathway_stage counter")
        for name in sorted(stages):
            lines.append(
                f'pathway_stage_total{{stage="{_escape_label(name)}"}} '
                f"{_format_value(stages[name])}"
            )
    totals = _profile.get_profiler().operator_totals()
    if totals:
        for family, key, help_text in (
            ("pathway_operator_seconds", "seconds", "Wall seconds per operator"),
            ("pathway_operator_rows", "rows", "Delta rows emitted per operator"),
            (
                "pathway_operator_retractions",
                "retractions",
                "Retraction rows emitted per operator",
            ),
        ):
            lines.append(f"# HELP {family} {help_text}")
            lines.append(f"# TYPE {family} counter")
            for entry in totals:
                lines.append(
                    f'{family}_total{{operator="{_escape_label(entry["name"])}"'
                    f',kind="{_escape_label(entry["kind"])}"'
                    f',node="{entry["node"]}"}} '
                    f"{_format_value(entry[key])}"
                )
    hists = _profile.histograms()
    for hist_name in sorted(hists):
        hist = hists[hist_name]
        if hist.count == 0:
            continue
        lines.extend(
            hist.openmetrics_lines(hist_name, f"Log-bucketed {hist_name}")
        )
    return lines


class ProberStats:
    """Shared run statistics, updated by the commit loop (reference ``graph.rs:554``)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.started = time.time()
        self.last_input_time: Optional[float] = None
        self.last_output_time: Optional[float] = None
        self.input_finished = False
        self.rows_by_node: Dict[int, int] = {}
        self.input_rows = 0
        self.output_rows = 0
        self.commits = 0

    def record_commit(
        self, input_rows: int, output_rows: int, row_counts: Dict[int, int], finished: bool
    ) -> None:
        now = time.time()
        with self.lock:
            self.commits += 1
            if input_rows:
                self.last_input_time = now
                self.input_rows += input_rows
            if output_rows:
                self.last_output_time = now
                self.output_rows += output_rows
            for nid, n in row_counts.items():
                self.rows_by_node[nid] = self.rows_by_node.get(nid, 0) + n
            self.input_finished = finished

    def _latencies_locked(self, now: float) -> tuple:
        """(input_latency_ms, output_latency_ms); -1 when input is finished.
        Caller holds ``self.lock`` — the single home of the -1/started-fallback
        convention shared by the /status endpoint and the OTel gauges."""
        if self.input_finished:
            return (-1, -1)
        base_in = self.last_input_time if self.last_input_time is not None else self.started
        base_out = self.last_output_time if self.last_output_time is not None else self.started
        return (int((now - base_in) * 1000), int((now - base_out) * 1000))

    def latencies_ms(self) -> tuple:
        now = time.time()
        with self.lock:
            return self._latencies_locked(now)

    def to_openmetrics(self) -> str:
        """Full metrics plane as one OpenMetrics exposition: the run-level
        gauges/counters, every stage counter (exchange bytes/frames, barrier
        waits, embed pipeline, …) as a ``stage``-labeled counter family,
        per-operator wall-time/row/retraction totals labeled by operator
        name/kind, and every registered log-bucketed histogram (commit
        duration, REST latency) as a real histogram family."""
        now = time.time()
        with self.lock:
            input_latency, output_latency = self._latencies_locked(now)
            lines = [
                "# HELP input_latency_ms A latency of input in milliseconds (-1 when finished)",
                "# TYPE input_latency_ms gauge",
                f"input_latency_ms {input_latency}",
                "# HELP output_latency_ms A latency of output in milliseconds (-1 when finished)",
                "# TYPE output_latency_ms gauge",
                f"output_latency_ms {output_latency}",
                "# HELP input_rows A counter of rows ingested by input connectors",
                "# TYPE input_rows counter",
                f"input_rows_total {self.input_rows}",
                "# HELP output_rows A counter of rows delivered to sinks",
                "# TYPE output_rows counter",
                f"output_rows_total {self.output_rows}",
                "# HELP commits A counter of engine commits executed",
                "# TYPE commits counter",
                f"commits_total {self.commits}",
            ]
        lines.extend(metrics_plane_lines())
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


class MonitoringServer:
    """Serves ``/status``+``/metrics`` (OpenMetrics) and ``/healthz`` (JSON
    liveness: per-peer heartbeat age, commit progress — the same payload the
    commit loop publishes to the supervisor's status file, so the supervisor
    and external probes share one signal)."""

    def __init__(self, stats: ProberStats, port: int):
        self.stats = stats
        # callable returning the liveness dict; installed by the GraphRunner
        # once the cluster exchange exists (None -> minimal alive response)
        self.health_source: Optional[Any] = None
        stats_ref = stats
        server_ref = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                if self.path == "/healthz":
                    import json as _json

                    source = server_ref.health_source
                    try:
                        payload = source() if source is not None else {}
                    except Exception as exc:  # a probe must never 500 a worker
                        # ...but a failing probe callback is NOT healthy
                        # either: keep HTTP 200 + alive (the process serves),
                        # and surface the degradation instead of masking it
                        # behind a synthetic "running". Typed peer errors are
                        # triaged first (PWA202 discipline): a probe aborted
                        # by the epoch fence means the worker is FENCING, a
                        # recoverable protocol state the supervisor reads —
                        # not a generic degradation
                        from pathway_tpu.parallel.cluster import (
                            PeerShutdownError,
                            PeerTimeoutError,
                        )

                        state = (
                            "fencing"
                            if isinstance(exc, (PeerShutdownError, PeerTimeoutError))
                            else "degraded"
                        )
                        payload = {"error": str(exc), "state": state}
                    payload.setdefault("alive", True)
                    # degraded-cluster observability: the runner reports
                    # "fencing"/"rejoining" during a surgical restart, plus
                    # cluster_epoch / restart counts / last-rejoin duration;
                    # a pre-cluster probe still reads as a running worker
                    payload.setdefault("state", "running")
                    body = _json.dumps(payload, sort_keys=True).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path not in ("/status", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = stats_ref.to_openmetrics().encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/openmetrics-text")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args: Any) -> None:
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True, name="pathway:monitoring-http"
        )
        self.thread.start()

    def close(self) -> None:
        """Idempotent: stop serving AND close the listener socket — a leaked
        listener holds the port across back-to-back runs in one process."""
        httpd, self.httpd = self.httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()


def maybe_start_http_server(stats: ProberStats, enabled: bool) -> Optional[MonitoringServer]:
    if not enabled:
        return None
    from pathway_tpu.internals.config import get_pathway_config

    cfg = get_pathway_config()
    base = cfg.monitoring_http_port or DEFAULT_MONITORING_HTTP_PORT
    port = base + cfg.process_id
    try:
        return MonitoringServer(stats, port)
    except OSError as exc:
        import logging

        logging.getLogger("pathway_tpu").warning(
            "monitoring HTTP endpoint requested but port %d is unavailable: %s", port, exc
        )
        return None
