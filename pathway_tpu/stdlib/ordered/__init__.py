"""Ordered-stream helpers (parity: reference ``stdlib/ordered/diff.py:10``)."""

from __future__ import annotations

from typing import Any

import pathway_tpu.internals.expression as expr
from pathway_tpu.internals.table import Table


def diff(table: Table, timestamp: Any, *values: Any, instance: Any = None) -> Table:
    """Per-row difference vs the previous row when ordered by ``timestamp``.

    Produces ``diff_<name>`` columns (None for the first row of each instance).
    """
    sorted_t = table.sort(timestamp, instance=instance)
    prev_table = table.ix(sorted_t.prev, optional=True)
    out_exprs: dict[str, Any] = {}
    for v in values:
        name = v.name if hasattr(v, "name") else str(v)
        out_exprs["diff_" + name] = expr.require(table[name] - prev_table[name], prev_table[name])
    return table.with_columns(**out_exprs)
