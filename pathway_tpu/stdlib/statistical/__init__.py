"""Statistical helpers (parity: reference ``stdlib/statistical`` — interpolate).

``interpolate`` resolves each None cell against the NEAREST non-None neighbors
in timestamp order — across arbitrarily long runs of Nones, like the reference
(``_interpolate.py:12`` reached through its iterate-closed prev/next chains):
nearest-known (t, v) pairs propagate along sort-order pointers to a fixpoint
with ``pw.iterate`` (pointer doubling, O(log run-length) rounds), then one pass
computes the blend. Chain state carries explicit validity flags — float columns
materialize None as NaN, so None-sentinels cannot drive the propagation.
"""

from __future__ import annotations

import enum
from typing import Any

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.table import Table


class InterpolateMode(enum.Enum):
    LINEAR = "linear"


def interpolate(
    table: Table, timestamp: Any, *values: Any, mode: InterpolateMode | None = None
) -> Table:
    """Linearly interpolate missing (None) values along ``timestamp`` order."""
    import pathway_tpu as pw

    mode = mode or InterpolateMode.LINEAR
    ts_name = timestamp.name if hasattr(timestamp, "name") else str(timestamp)
    names = [v.name if hasattr(v, "name") else str(v) for v in values]

    sorted_t = table.sort(timestamp)

    def _known(v: Any) -> bool:
        # missing = None OR NaN: float columns materialize absent cells as NaN
        return v is not None and v == v

    result = table
    for name in names:
        known = expr.apply_with_type(_known, bool, table[name])
        state0 = table.select(
            prev_ptr=sorted_t.prev,
            next_ptr=sorted_t.next,
            t=table[ts_name],
            cur=table[name],
            ok=known,
            pt=expr.if_else(known, table[ts_name], 0.0 * table[ts_name]),
            pv=expr.coalesce(table[name], 0.0),
            p_ok=known,
            nt=expr.if_else(known, table[ts_name], 0.0 * table[ts_name]),
            nv=expr.coalesce(table[name], 0.0),
            n_ok=known,
        )

        def step(state: Table) -> Table:
            prev_row = state.ix(state.prev_ptr, optional=True)
            next_row = state.ix(state.next_ptr, optional=True)
            prev_ok = expr.coalesce(prev_row.p_ok, False)
            next_ok = expr.coalesce(next_row.n_ok, False)
            return state.select(
                # pointer doubling: an unresolved row whose neighbor is also
                # unresolved jumps over it, so a None-run of length L closes in
                # O(log L) iterations
                prev_ptr=expr.if_else(
                    ~state.p_ok & ~prev_ok, prev_row.prev_ptr, state.prev_ptr
                ),
                next_ptr=expr.if_else(
                    ~state.n_ok & ~next_ok, next_row.next_ptr, state.next_ptr
                ),
                t=state.t,
                cur=state.cur,
                ok=state.ok,
                pt=expr.if_else(state.p_ok, state.pt, expr.coalesce(prev_row.pt, 0.0)),
                pv=expr.if_else(state.p_ok, state.pv, expr.coalesce(prev_row.pv, 0.0)),
                p_ok=state.p_ok | prev_ok,
                nt=expr.if_else(state.n_ok, state.nt, expr.coalesce(next_row.nt, 0.0)),
                nv=expr.if_else(state.n_ok, state.nv, expr.coalesce(next_row.nv, 0.0)),
                n_ok=state.n_ok | next_ok,
            )

        resolved = pw.iterate(lambda state: dict(state=step(state)), state=state0).state
        resolved.promise_universe_is_equal_to(table)
        aligned = resolved.with_universe_of(table)

        def interp(
            t: Any, cur: Any, pt: Any, pv: Any, p_ok: Any, nt: Any, nv: Any, n_ok: Any
        ) -> Any:
            if cur is not None and cur == cur:
                return cur
            if p_ok and n_ok and nt != pt:
                return pv + (nv - pv) * (t - pt) / (nt - pt)
            if p_ok:
                return pv
            if n_ok:
                return nv
            return None

        # emit from the ITERATED table (update_cells reacts to patch-side
        # deltas): a late-arriving known point re-resolves chains inside the
        # iterate, and the re-interpolated cells must flow even though the base
        # rows saw no delta of their own
        filled = aligned.select(
            **{
                name: expr.apply_with_type(
                    interp,
                    float,
                    aligned.t,
                    aligned.cur,
                    aligned.pt,
                    aligned.pv,
                    aligned.p_ok,
                    aligned.nt,
                    aligned.nv,
                    aligned.n_ok,
                )
            }
        )
        result = result.update_cells(filled)
    return result
