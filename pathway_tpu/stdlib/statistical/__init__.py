"""Statistical helpers (parity: reference ``stdlib/statistical`` — interpolate)."""

from __future__ import annotations

import enum
from typing import Any

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.table import Table


class InterpolateMode(enum.Enum):
    LINEAR = "linear"


def interpolate(
    table: Table, timestamp: Any, *values: Any, mode: InterpolateMode | None = None
) -> Table:
    """Linearly interpolate missing (None) values along ``timestamp`` order."""
    mode = mode or InterpolateMode.LINEAR
    sorted_t = table.sort(timestamp)
    prev_t = table.ix(sorted_t.prev, optional=True)
    next_t = table.ix(sorted_t.next, optional=True)
    ts_name = timestamp.name if hasattr(timestamp, "name") else str(timestamp)

    out_exprs: dict[str, Any] = {}
    for v in values:
        name = v.name if hasattr(v, "name") else str(v)

        def make_interp(name: str = name) -> Any:
            def interp(t: Any, cur: Any, pt: Any, pv: Any, nt: Any, nv: Any) -> Any:
                if cur is not None:
                    return cur
                if pv is not None and nv is not None and nt != pt:
                    return pv + (nv - pv) * (t - pt) / (nt - pt)
                if pv is not None:
                    return pv
                return nv

            return expr.apply_with_type(
                interp,
                float,
                table[ts_name],
                table[name],
                prev_t[ts_name],
                prev_t[name],
                next_t[ts_name],
                next_t[name],
            )

        out_exprs[name] = make_interp()
    return table.with_columns(**out_exprs)
