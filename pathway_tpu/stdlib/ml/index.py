"""KNNIndex — the classic python-side index API.

Parity: reference ``stdlib/ml/index.py:9`` (wraps the LSH flat classifier there; here it wraps
the TPU brute-force / LSH kernels through DataIndex). This is BASELINE benchmark config #1.
"""

from __future__ import annotations

from typing import Any, Optional

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.data_index import DataIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnn,
    BruteForceKnnMetricKind,
    LshKnn,
)


class KNNIndex:
    """K-nearest-neighbors over a vector column.

    ``get_nearest_items(query_embeddings, k)`` returns, per query row, tuples of the data
    table's columns for the k nearest vectors (reference semantics incl. ``query_id`` and
    metadata filters).
    """

    def __init__(
        self,
        data_embedding: expr.ColumnReference,
        data: Table,
        n_dimensions: int,
        n_or: int = 20,
        n_and: int = 10,
        bucket_length: float = 10.0,
        distance_type: str = "euclidean",
        metadata: expr.ColumnReference | None = None,
        exact: bool = True,
        approximate: str = "lsh",
        n_clusters: int = 64,
        n_probe: int = 8,
    ):
        self.data = data
        if approximate not in ("lsh", "ivf"):
            raise ValueError(
                f"approximate={approximate!r} is not a KNNIndex mode; use 'lsh' or 'ivf'"
            )
        if exact and approximate != "lsh":
            # exact=True (the default) would silently shadow an explicit ANN
            # request with brute force — make the contradiction loud
            raise ValueError(
                f"approximate={approximate!r} requires exact=False "
                "(exact=True always builds the brute-force index)"
            )
        metric = (
            BruteForceKnnMetricKind.COS
            if distance_type == "cosine"
            else BruteForceKnnMetricKind.L2SQ
        )
        if exact:
            inner: Any = BruteForceKnn(
                data_embedding, metadata, dimensions=n_dimensions, metric=metric
            )
        elif approximate == "ivf":
            # sublinear candidate selection through the fused IVF kernel
            # (ops/knn_ivf.py) instead of LSH bucket intersection
            from pathway_tpu.stdlib.indexing.nearest_neighbors import IvfKnn

            inner = IvfKnn(
                data_embedding,
                metadata,
                dimensions=n_dimensions,
                metric=metric,
                n_clusters=n_clusters,
                n_probe=n_probe,
            )
        else:
            inner = LshKnn(
                data_embedding,
                metadata,
                dimensions=n_dimensions,
                n_or=n_or,
                n_and=n_and,
                bucket_length=bucket_length,
                distance_type=distance_type,
            )
        self.index = DataIndex(data, inner)

    def get_nearest_items(
        self,
        query_embedding: expr.ColumnReference,
        k: Any = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: expr.ColumnExpression | None = None,
    ) -> Table:
        result = self.index.query(
            query_embedding,
            number_of_matches=k,
            collapse_rows=collapse_rows,
            metadata_filter=metadata_filter,
        )
        if with_distances:
            result = result.with_columns(dist=result._pw_index_reply_score)
        return result

    def get_nearest_items_asof_now(
        self,
        query_embedding: expr.ColumnReference,
        k: Any = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: expr.ColumnExpression | None = None,
    ) -> Table:
        return self.index.query_as_of_now(
            query_embedding,
            number_of_matches=k,
            collapse_rows=collapse_rows,
            metadata_filter=metadata_filter,
        )
