"""Classification dataset loaders (parity: reference
``stdlib/ml/datasets/classification`` — MNIST via OpenML, train/test table split).
"""

from __future__ import annotations

import numpy as np

from pathway_tpu.debug import table_from_pandas


def _tables_from_arrays(X_train, y_train, X_test, y_test):
    import pandas as pd

    X_train_table = table_from_pandas(
        pd.DataFrame({"data": [np.asarray(x) for x in X_train]})
    )
    y_train_table = table_from_pandas(pd.DataFrame({"label": list(y_train)}))
    X_test_table = table_from_pandas(
        pd.DataFrame({"data": [np.asarray(x) for x in X_test]})
    )
    y_test_table = table_from_pandas(pd.DataFrame({"label": list(y_test)}))
    return X_train_table, y_train_table, X_test_table, y_test_table


def load_mnist_sample(sample_size: int = 70_000):
    """MNIST via OpenML, split 6:1 into train/test tables of (data, label)
    (reference ``load_mnist_sample``). Needs scikit-learn and network access."""
    try:
        from sklearn.datasets import fetch_openml
    except ImportError as e:
        raise ImportError(
            "scikit-learn is required for load_mnist_sample; for an offline "
            "dataset use load_synthetic_classification"
        ) from e
    X, y = fetch_openml("mnist_784", version=1, return_X_y=True, as_frame=False)
    X = X / 255.0
    train_size = int(sample_size * 6 / 7)
    test_size = sample_size // 7
    return _tables_from_arrays(
        X[:60_000][:train_size],
        y[:60_000][:train_size],
        X[60_000:70_000][:test_size],
        y[60_000:70_000][:test_size],
    )


def load_synthetic_classification(
    n_train: int = 600, n_test: int = 100, dim: int = 16, n_classes: int = 4, seed: int = 0
):
    """Offline stand-in with the same table contract as ``load_mnist_sample``:
    Gaussian blobs, one cluster per class (for tests and zero-egress images)."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=4.0, size=(n_classes, dim))

    def make(n):
        labels = rng.integers(0, n_classes, n)
        data = centers[labels] + rng.normal(size=(n, dim))
        return data.astype(np.float64), [str(l) for l in labels.tolist()]

    X_train, y_train = make(n_train)
    X_test, y_test = make(n_test)
    return _tables_from_arrays(X_train, y_train, X_test, y_test)
