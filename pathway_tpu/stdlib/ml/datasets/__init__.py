"""Dataset loaders (parity: reference ``stdlib/ml/datasets``)."""

from pathway_tpu.stdlib.ml.datasets.classification import (
    load_mnist_sample,
    load_synthetic_classification,
)

__all__ = ["load_mnist_sample", "load_synthetic_classification"]
