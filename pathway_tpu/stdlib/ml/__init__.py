"""ML stdlib (parity: reference ``stdlib/ml``)."""

from pathway_tpu.stdlib.ml import index
from pathway_tpu.stdlib.ml.index import KNNIndex

__all__ = ["KNNIndex", "index"]
