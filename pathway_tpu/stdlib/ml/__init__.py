"""ML stdlib (parity: reference ``stdlib/ml``)."""

from pathway_tpu.stdlib.ml import index
from pathway_tpu.stdlib.ml.index import KNNIndex
from pathway_tpu.stdlib.ml import hmm
from pathway_tpu.stdlib.ml import smart_table_ops
from pathway_tpu.stdlib.ml import datasets
from pathway_tpu.stdlib.ml.smart_table_ops import (
    fuzzy_match,
    fuzzy_match_tables,
    fuzzy_self_match,
    smart_fuzzy_match,
)

__all__ = [
    "KNNIndex",
    "index",
    "hmm",
    "smart_table_ops",
    "datasets",
    "fuzzy_match",
    "fuzzy_match_tables",
    "fuzzy_self_match",
    "smart_fuzzy_match",
]
