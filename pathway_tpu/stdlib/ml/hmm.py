"""Incremental Hidden-Markov-Model decoding as a custom reducer (parity:
reference ``stdlib/ml/hmm.py:create_hmm_reducer``).

The reducer consumes a stream of observations grouped per key and maintains a
Viterbi beam incrementally: each new observation advances per-state best
log-probabilities and back-paths in one pass over the transition graph — no
re-decode of the history, so a long-running stream pays O(states * degree) per
update. Used as ``pw.reducers.udf_reducer(create_hmm_reducer(graph))``.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.custom_reducers import BaseCustomAccumulator


def create_hmm_reducer(
    graph: Any,
    beam_size: int | None = None,
    num_results_kept: int | None = None,
) -> type:
    """Build an accumulator class decoding the HMM described by ``graph``.

    ``graph``: a ``networkx.DiGraph`` whose nodes carry ``calc_emission_log_ppb``
    (callable observation -> log-probability), edges carry
    ``log_transition_ppb``, and ``graph.graph["start_nodes"]`` lists initial
    states. ``beam_size`` keeps only the top-k states per step;
    ``num_results_kept`` bounds the reported path suffix (and the stored
    back-paths, so memory stays constant over unbounded streams).
    """
    start_nodes = list(graph.graph.get("start_nodes", graph.nodes))
    emission = {s: graph.nodes[s]["calc_emission_log_ppb"] for s in graph.nodes}
    transitions: dict[Any, list[tuple[Any, float]]] = {
        s: [
            (succ, float(graph.edges[s, succ]["log_transition_ppb"]))
            for succ in graph.successors(s)
        ]
        for s in graph.nodes
    }
    keep = num_results_kept

    def advance(beam: dict | None, obs: Any) -> dict:
        if beam is None:
            new = {
                s: (float(emission[s](obs)), (s,))
                for s in start_nodes
            }
        else:
            new = {}
            for s1, (lp, path) in beam.items():
                for s2, trans_lp in transitions[s1]:
                    cand = lp + trans_lp + float(emission[s2](obs))
                    cur = new.get(s2)
                    if cur is None or cand > cur[0]:
                        suffix = path + (s2,)
                        if keep is not None:
                            suffix = suffix[-keep:]
                        new[s2] = (cand, suffix)
        if beam_size is not None and len(new) > beam_size:
            top = sorted(new.items(), key=lambda kv: -kv[1][0])[:beam_size]
            new = dict(top)
        return new

    class HmmAccumulator(BaseCustomAccumulator):
        def __init__(self, observations: list):
            self.pending = list(observations)
            self.beam: dict | None = None

        @classmethod
        def from_row(cls, row: list) -> "HmmAccumulator":
            return cls([row[0]])

        def _drain(self) -> None:
            for obs in self.pending:
                self.beam = advance(self.beam, obs)
            self.pending = []

        def update(self, other: "HmmAccumulator") -> None:
            self._drain()
            for obs in other.pending:
                self.beam = advance(self.beam, obs)

        def compute_result(self) -> tuple:
            self._drain()
            if not self.beam:
                return ()
            _, path = max(self.beam.values(), key=lambda v: v[0])
            return tuple(path)

    return HmmAccumulator
