"""Fuzzy joins over token features (parity: reference
``stdlib/ml/smart_table_ops/_fuzzy_join.py:106-470``).

Own design on the engine's incremental relational ops: rows tokenize into
feature edges (``flatten``), features weight by inverse corpus frequency
(the reference's normalization step), candidate pairs score by summed shared
feature weight through a token-equijoin + groupby — the hot path rides the
engine's vectorized join/segment kernels — and the final matching keeps
MUTUAL-BEST pairs (a pair survives iff it is the heaviest candidate for both
its left and its right node; the reference reaches a similar fixpoint through
an iterative heaviest-pair selection).
"""

from __future__ import annotations

import re
from enum import IntEnum
from typing import Any, Callable

import pathway_tpu as pw
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.table import Table


class FuzzyJoinFeatureGeneration(IntEnum):
    AUTO = 0
    WORDS = 1
    LETTERS = 2
    TRIGRAMS = 3

    @property
    def generate(self) -> Callable[[Any], list]:
        return {
            FuzzyJoinFeatureGeneration.AUTO: _tokenize_words,
            FuzzyJoinFeatureGeneration.WORDS: _tokenize_words,
            FuzzyJoinFeatureGeneration.LETTERS: _tokenize_letters,
            FuzzyJoinFeatureGeneration.TRIGRAMS: _tokenize_trigrams,
        }[self]


class FuzzyJoinNormalization(IntEnum):
    NONE = 0
    INVERSE_COUNT = 1
    LOG_INVERSE = 2

    def weight(self, cnt: float) -> float:
        import math

        if self is FuzzyJoinNormalization.NONE:
            return 1.0
        if self is FuzzyJoinNormalization.INVERSE_COUNT:
            return 1.0 / max(cnt, 1.0)
        return 1.0 / max(math.log2(max(cnt, 1.0)) + 1.0, 1.0)


def _tokenize_words(obj: Any) -> list:
    return [w.lower() for w in re.findall(r"\w+", str(obj))]


def _tokenize_letters(obj: Any) -> list:
    return [c.lower() for c in str(obj) if not c.isspace()]


def _tokenize_trigrams(obj: Any) -> list:
    s = str(obj).lower()
    return [s[i : i + 3] for i in range(max(1, len(s) - 2))]


def _token_edges(col: expr.ColumnReference, generation: FuzzyJoinFeatureGeneration) -> Table:
    """(node, token) edge table for one side."""
    tokenize = generation.generate
    base = col.table.select(
        _fz_text=col,
    )
    with_tokens = base.select(
        _fz_tokens=pw.apply_with_type(
            lambda t: tuple(tokenize(t)), tuple, base._fz_text
        ),
    )
    return with_tokens.flatten(pw.this._fz_tokens, origin_id="node").select(
        token=pw.this._fz_tokens, node=pw.this.node
    )


def fuzzy_match(
    left_col: expr.ColumnReference,
    right_col: expr.ColumnReference,
    *,
    generation: FuzzyJoinFeatureGeneration = FuzzyJoinFeatureGeneration.AUTO,
    normalization: FuzzyJoinNormalization = FuzzyJoinNormalization.INVERSE_COUNT,
    _exclude_same_node: bool = False,
) -> Table:
    """Best-pair matching between two text columns.

    Returns a table with columns ``left`` (pointer into the left table),
    ``right`` (pointer into the right table) and ``weight`` — one row per
    mutual-best pair (reference ``fuzzy_match``, ``_fuzzy_join.py:265``).
    """
    left_edges = _token_edges(left_col, generation)
    right_edges = _token_edges(right_col, generation)

    all_edges = left_edges.concat_reindex(right_edges)
    token_cnt = all_edges.groupby(pw.this.token).reduce(
        pw.this.token, cnt=pw.reducers.count()
    )
    norm = normalization
    token_weight = token_cnt.select(
        pw.this.token,
        w=pw.apply_with_type(lambda c: norm.weight(float(c)), float, pw.this.cnt),
    )

    weighted_left = left_edges.join(
        token_weight, left_edges.token == token_weight.token
    ).select(left_edges.node, left_edges.token, token_weight.w)

    pair_scores = (
        weighted_left.join(right_edges, weighted_left.token == right_edges.token)
        .select(left=weighted_left.node, right=right_edges.node, w=weighted_left.w)
        .groupby(pw.this.left, pw.this.right)
        .reduce(pw.this.left, pw.this.right, weight=pw.reducers.sum(pw.this.w))
    )
    if _exclude_same_node:
        # self-matching: a row's heaviest candidate is always itself — drop
        # identity pairs BEFORE best-selection or nothing else can ever win
        pair_scores = pair_scores.filter(
            pw.apply_with_type(lambda l, r: l != r, bool, pw.this.left, pw.this.right)
        )

    best_left = pair_scores.groupby(pw.this.left).reduce(
        pw.this.left, best=pw.reducers.max(pw.this.weight)
    )
    best_right = pair_scores.groupby(pw.this.right).reduce(
        pw.this.right, best=pw.reducers.max(pw.this.weight)
    )
    with_left = pair_scores.join(
        best_left, pair_scores.left == best_left.left
    ).select(
        pair_scores.left, pair_scores.right, pair_scores.weight, lbest=best_left.best
    )
    with_both = with_left.join(
        best_right, with_left.right == best_right.right
    ).select(
        with_left.left,
        with_left.right,
        with_left.weight,
        with_left.lbest,
        rbest=best_right.best,
    )
    return with_both.filter(
        (pw.this.weight == pw.this.lbest) & (pw.this.weight == pw.this.rbest)
    ).select(pw.this.left, pw.this.right, pw.this.weight)


def fuzzy_self_match(
    col: expr.ColumnReference,
    *,
    generation: FuzzyJoinFeatureGeneration = FuzzyJoinFeatureGeneration.AUTO,
    normalization: FuzzyJoinNormalization = FuzzyJoinNormalization.INVERSE_COUNT,
) -> Table:
    """Mutual-best pairs WITHIN one column (reference ``fuzzy_self_match:249``);
    each unordered pair reports once (left < right) and self-pairs are dropped."""
    matches = fuzzy_match(
        col,
        col,
        generation=generation,
        normalization=normalization,
        _exclude_same_node=True,
    )
    return matches.filter(
        pw.apply_with_type(lambda l, r: l < r, bool, pw.this.left, pw.this.right)
    )


def _concat_row_text(table: Table) -> Table:
    cols = [table[c] for c in table.column_names()]
    return table.select(
        _fz_all=pw.apply_with_type(
            lambda *vals: " ".join(str(v) for v in vals), str, *cols
        )
    )


def fuzzy_match_tables(
    left_table: Table,
    right_table: Table,
    *,
    left_projection: dict | None = None,
    right_projection: dict | None = None,
    generation: FuzzyJoinFeatureGeneration = FuzzyJoinFeatureGeneration.AUTO,
    normalization: FuzzyJoinNormalization = FuzzyJoinNormalization.INVERSE_COUNT,
) -> Table:
    """Match whole rows of two tables by concatenated column text
    (reference ``fuzzy_match_tables:106``). Projections, when given, select the
    columns to concatenate per side ({column_name: anything} mappings)."""
    lt = left_table
    rt = right_table
    if left_projection:
        lt = left_table.select(*[left_table[c] for c in left_projection])
    if right_projection:
        rt = right_table.select(*[right_table[c] for c in right_projection])
    left_text = _concat_row_text(lt)
    right_text = _concat_row_text(rt)
    return fuzzy_match(
        left_text._fz_all,
        right_text._fz_all,
        generation=generation,
        normalization=normalization,
    )


def smart_fuzzy_match(
    left_col: expr.ColumnReference,
    right_col: expr.ColumnReference,
    **kwargs: Any,
) -> Table:
    """Reference ``smart_fuzzy_match:199``. The reference iterates heaviest-pair
    selection with provision lists; here the mutual-best fixpoint of
    :func:`fuzzy_match` stands in (same result on non-degenerate weights)."""
    return fuzzy_match(left_col, right_col, **kwargs)
