"""Fuzzy-matching table ops (parity: reference ``stdlib/ml/smart_table_ops``)."""

from pathway_tpu.stdlib.ml.smart_table_ops._fuzzy_join import (
    FuzzyJoinFeatureGeneration,
    FuzzyJoinNormalization,
    fuzzy_match,
    fuzzy_match_tables,
    fuzzy_self_match,
    smart_fuzzy_match,
)

__all__ = [
    "FuzzyJoinFeatureGeneration",
    "FuzzyJoinNormalization",
    "fuzzy_match",
    "fuzzy_match_tables",
    "fuzzy_self_match",
    "smart_fuzzy_match",
]
