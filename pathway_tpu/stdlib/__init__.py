"""Standard library (parity: reference ``python/pathway/stdlib/``)."""
