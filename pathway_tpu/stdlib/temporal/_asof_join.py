"""Asof joins (parity: reference ``stdlib/temporal/_asof_join.py:479-1000`` and
``_asof_now_join.py:176-332``).

Mechanism: the right side aggregates per join-key into a sorted (time, rowid) tuple; each left
row binary-searches it for the latest-not-after (backward) / earliest-not-before (forward)
match. Incremental via groupby+ix (right updates re-trigger affected left rows).
"""

from __future__ import annotations

import bisect
import enum
from typing import Any, Dict

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.joins import JoinKind
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.table import Table, _name_of
from pathway_tpu.internals import thisclass


class AsofDirection(enum.Enum):
    BACKWARD = "backward"
    FORWARD = "forward"
    NEAREST = "nearest"


Direction = AsofDirection


class AsofJoinResult:
    def __init__(
        self,
        left: Table,
        right: Table,
        left_time: expr.ColumnExpression,
        right_time: expr.ColumnExpression,
        on: tuple,
        kind: JoinKind,
        direction: AsofDirection,
        defaults: Dict[Any, Any] | None = None,
    ):
        self.left = left
        self.right = right
        self.left_time = left_time
        self.right_time = right_time
        self.on = on
        self.kind = kind
        self.direction = direction
        self.defaults = defaults or {}

    def _split_on(self) -> tuple[list, list]:
        import operator

        left_on: list[expr.ColumnExpression] = []
        right_on: list[expr.ColumnExpression] = []
        for cond in self.on:
            cond = thisclass.substitute(
                cond, {thisclass.left: self.left, thisclass.right: self.right}
            )
            assert (
                isinstance(cond, expr.ColumnBinaryOpExpression)
                and cond._operator is operator.eq
            ), "asof_join conditions must be equalities"
            a, b = cond._left, cond._right
            if any(r.table is self.left for r in a._column_refs):
                left_on.append(a)
                right_on.append(b)
            else:
                left_on.append(b)
                right_on.append(a)
        return left_on, right_on

    def select(self, *args: Any, **kwargs: Any) -> Table:
        """Reference asof semantics (``_asof_join.py:479-1000``): every record of a
        participating side yields one output row, matched against the OTHER side's
        record selected by ``direction`` (backward = latest not-after). LEFT drives
        from the left records, RIGHT from the right, OUTER from both; ``pw.this``
        additionally exposes ``instance`` (join-key value), ``side`` (False =
        left-driven) and ``t`` (the driving record's time)."""
        out_exprs: Dict[str, Any] = {}
        for arg in args:
            out_exprs[_name_of(arg)] = arg
        out_exprs.update(kwargs)

        left_on, right_on = self._split_on()
        parts: list[Table] = []
        if self.kind in (JoinKind.INNER, JoinKind.LEFT, JoinKind.OUTER):
            parts.append(self._side_part(False, left_on, right_on, out_exprs))
        if self.kind in (JoinKind.RIGHT, JoinKind.OUTER):
            parts.append(self._side_part(True, left_on, right_on, out_exprs))
        if len(parts) == 1:
            return parts[0]
        return parts[0].concat_reindex(*parts[1:])

    def _side_part(
        self, flipped: bool, left_on: list, right_on: list, out_exprs: Dict[str, Any]
    ) -> Table:
        if not flipped:
            driver, other = self.left, self.right
            driver_time, other_time = self.left_time, self.right_time
            driver_on, other_on = left_on, right_on
        else:
            driver, other = self.right, self.left
            driver_time, other_time = self.right_time, self.left_time
            driver_on, other_on = right_on, left_on

        ot = other.with_columns(_pw_t=other_time)
        ot2 = ot.with_columns(_pw_pair=expr.make_tuple(ot._pw_t, ot.id))
        if other_on:
            # group by the RAW key expressions: the group's output key is then
            # keys_from_values(values) == pointer_from(values), exactly what the
            # driver side derives for its ix lookup
            key_cols = {
                f"_pw_k{i}": _rebind_to(e, other, ot2) for i, e in enumerate(other_on)
            }
            keyed = ot2.with_columns(**key_cols)
            agg = keyed.groupby(*[keyed[n] for n in key_cols]).reduce(
                _pw_pairs=reducers.sorted_tuple(keyed._pw_pair)
            )
        else:
            agg = ot2.groupby().reduce(_pw_pairs=reducers.sorted_tuple(ot2._pw_pair))

        dt = driver.with_columns(_pw_t=driver_time)
        if driver_on:
            dkey = dt.pointer_from(*[_rebind_to(e, driver, dt) for e in driver_on])
        else:
            dkey = dt.pointer_from()
        pairs = agg.ix(dkey, optional=True)._pw_pairs

        direction = self.direction

        def pick(mytime: Any, pairs_tuple: Any) -> Any:
            # Tie-break follows the reference's merge order: at equal times, LEFT
            # events precede RIGHT events. A left-driven row therefore sees
            # same-time right rows as "after" it (backward excludes them, forward
            # includes them); a right-driven row sees same-time left rows as
            # "before" (backward inclusive, forward exclusive).
            if not pairs_tuple:
                return None
            times = [p[0] for p in pairs_tuple]
            inclusive_back = flipped  # right-driven: at-or-before
            if direction == AsofDirection.BACKWARD:
                i = (
                    bisect.bisect_right(times, mytime)
                    if inclusive_back
                    else bisect.bisect_left(times, mytime)
                ) - 1
                return pairs_tuple[i][1] if i >= 0 else None
            if direction == AsofDirection.FORWARD:
                i = (
                    bisect.bisect_left(times, mytime)
                    if not flipped  # left-driven: at-or-after
                    else bisect.bisect_right(times, mytime)
                )
                return pairs_tuple[i][1] if i < len(pairs_tuple) else None
            # nearest
            i = bisect.bisect_left(times, mytime)
            best = None
            for j in (i - 1, i):
                if 0 <= j < len(pairs_tuple):
                    d = abs(times[j] - mytime)
                    if best is None or d < best[0]:
                        best = (d, pairs_tuple[j][1])
            return best[1] if best else None

        match_ptr = expr.apply_with_type(pick, Any, dt._pw_t, pairs)
        with_match = dt.with_columns(_pw_match=match_ptr)
        if self.kind == JoinKind.INNER:
            with_match = with_match.filter(with_match._pw_match.is_not_none())
        omatch = other.ix(with_match._pw_match, optional=True)

        specials: Dict[str, Any] = {
            "side": expr.ColumnConstExpression(flipped),
            "t": with_match._pw_t,
        }
        if driver_on:
            inst = [_rebind_to(e, driver, with_match) for e in driver_on]
            specials["instance"] = inst[0] if len(inst) == 1 else expr.make_tuple(*inst)
        else:
            specials["instance"] = expr.ColumnConstExpression(None)

        resolved = {}
        for name, e in out_exprs.items():
            # pw.this.instance/side/t resolve to the asof result's virtual columns
            e = _resolve_specials(e, specials)
            e = thisclass.substitute(
                e,
                {thisclass.left: self.left, thisclass.right: self.right, thisclass.this: driver},
            )
            resolved[name] = _rebind_asof(
                e, driver, with_match, other, omatch, self.defaults, specials
            )
        return with_match.select(**resolved)


def _name_of_expr(e: Any, table: Table) -> str:
    return e.name if isinstance(e, expr.ColumnReference) else str(e)


def _rebind_to(e: Any, old: Table, new: Table) -> Any:
    if isinstance(e, expr.ColumnReference):
        return new[e.name] if e.table is old else e
    if isinstance(e, expr.ColumnExpression):
        import copy

        clone = copy.copy(e)
        for attr, value in list(vars(e).items()):
            if isinstance(value, expr.ColumnExpression):
                setattr(clone, attr, _rebind_to(value, old, new))
            elif isinstance(value, tuple) and any(isinstance(v, expr.ColumnExpression) for v in value):
                setattr(
                    clone,
                    attr,
                    tuple(
                        _rebind_to(v, old, new) if isinstance(v, expr.ColumnExpression) else v
                        for v in value
                    ),
                )
        return clone
    return e


def _resolve_specials(e: Any, specials: Dict[str, Any]) -> Any:
    if isinstance(e, thisclass.ThisColumnReference) and e._kind is thisclass.this:
        # instance/side/t are the asof result's virtual columns and win over
        # same-named driver columns (pw.this.t is the merge time even when the
        # driver has a column "t" — reference test_asof_left_forward)
        if e.name in specials:
            return specials[e.name]
        return e
    if isinstance(e, expr.ColumnExpression) and not isinstance(e, expr.ColumnReference):
        import copy

        clone = copy.copy(e)
        for attr, value in list(vars(e).items()):
            if isinstance(value, expr.ColumnExpression):
                setattr(clone, attr, _resolve_specials(value, specials))
            elif isinstance(value, tuple) and any(
                isinstance(v, expr.ColumnExpression) for v in value
            ):
                setattr(
                    clone,
                    attr,
                    tuple(
                        _resolve_specials(v, specials)
                        if isinstance(v, expr.ColumnExpression)
                        else v
                        for v in value
                    ),
                )
        return clone
    return e


def _rebind_asof(
    e: Any,
    driver: Table,
    new_driver: Table,
    other: Table,
    omatch: Table,
    defaults: Dict,
    specials: Dict[str, Any],
) -> Any:
    """Rebind a select expression for one asof side-pass: driver refs hit the driving
    rows (``pw.this`` specials ``instance``/``side``/``t`` included), other-side refs
    hit the matched row with the configured default coalesced over a missing match."""
    if isinstance(e, expr.ColumnReference):
        if e.table is driver:
            if e.name in specials and e.name not in driver.column_names():
                return specials[e.name]
            return new_driver[e.name]
        if e.table is other:
            base = omatch[e.name]
            key = (id(other), e.name)
            if key in defaults:
                return expr.coalesce(base, defaults[key])
            return base
        return e
    if isinstance(e, expr.ColumnExpression):
        import copy

        clone = copy.copy(e)
        for attr, value in list(vars(e).items()):
            if isinstance(value, expr.ColumnExpression):
                setattr(
                    clone,
                    attr,
                    _rebind_asof(value, driver, new_driver, other, omatch, defaults, specials),
                )
            elif isinstance(value, tuple) and any(isinstance(v, expr.ColumnExpression) for v in value):
                setattr(
                    clone,
                    attr,
                    tuple(
                        _rebind_asof(v, driver, new_driver, other, omatch, defaults, specials)
                        if isinstance(v, expr.ColumnExpression)
                        else v
                        for v in value
                    ),
                )
        return clone
    return e


def asof_join(
    self: Table,
    other: Table,
    self_time: Any,
    other_time: Any,
    *on: Any,
    how: JoinKind = JoinKind.LEFT,
    defaults: Dict | None = None,
    direction: AsofDirection = AsofDirection.BACKWARD,
    behavior: Any = None,
) -> AsofJoinResult:
    defaults_by_ref: Dict[Any, Any] = {}
    if defaults:
        from pathway_tpu.internals import thisclass

        for k, v in defaults.items():
            # keyed by (owning table, column name): both sides may default the same
            # column name (reference ``defaults={t1.val: 0, t2.val: 0}``);
            # pw.left/pw.right keys substitute to their concrete tables first
            k = thisclass.substitute(k, {thisclass.left: self, thisclass.right: other})
            if isinstance(k, expr.ColumnReference):
                defaults_by_ref[(id(k.table), k.name)] = v
            else:
                defaults_by_ref[(id(other), k)] = v
    return AsofJoinResult(
        self,
        other,
        self._resolve(self_time),
        other._resolve(other_time),
        on,
        how,
        direction,
        defaults_by_ref,
    )


def asof_join_inner(self: Table, other: Table, self_time: Any, other_time: Any, *on: Any, **kw: Any) -> AsofJoinResult:
    kw.setdefault("how", JoinKind.INNER)
    return asof_join(self, other, self_time, other_time, *on, **kw)


def asof_join_left(self: Table, other: Table, self_time: Any, other_time: Any, *on: Any, **kw: Any) -> AsofJoinResult:
    kw.setdefault("how", JoinKind.LEFT)
    return asof_join(self, other, self_time, other_time, *on, **kw)


def asof_join_right(self: Table, other: Table, self_time: Any, other_time: Any, *on: Any, **kw: Any) -> AsofJoinResult:
    kw.setdefault("how", JoinKind.RIGHT)
    return asof_join(self, other, self_time, other_time, *on, **kw)


def asof_join_outer(self: Table, other: Table, self_time: Any, other_time: Any, *on: Any, **kw: Any) -> AsofJoinResult:
    kw.setdefault("how", JoinKind.OUTER)
    return asof_join(self, other, self_time, other_time, *on, **kw)


# -- asof_now: query-stream semantics (no retraction of answers) -------------


def asof_now_join(self: Table, other: Table, *on: Any, how: JoinKind = JoinKind.INNER, **kw: Any):
    """Join where ``self`` is a query stream answered as of now (reference
    ``_asof_now_join.py:176``)."""
    from pathway_tpu.stdlib.temporal._interval_join import _rebind

    forgotten = self._forget_immediately()
    # user expressions reference the original left table; rebind them onto the
    # forgetting copy (reference ``_asof_now_join.py:79-84``)
    on = tuple(_rebind(cond, self, forgotten, other, other) for cond in on)
    result = forgotten.join(other, *on, how=how, **kw)
    left_table = self

    class _AsofNowJoinResult:
        def select(self, *args: Any, **kwargs: Any) -> Table:
            args = tuple(
                _rebind(a, left_table, forgotten, other, other) for a in args
            )
            kwargs = {
                k: _rebind(v, left_table, forgotten, other, other)
                for k, v in kwargs.items()
            }
            selected = result.select(*args, **kwargs)
            return selected._filter_out_results_of_forgetting()

    return _AsofNowJoinResult()


def asof_now_join_inner(self: Table, other: Table, *on: Any, **kw: Any):
    return asof_now_join(self, other, *on, how=JoinKind.INNER, **kw)


def asof_now_join_left(self: Table, other: Table, *on: Any, **kw: Any):
    return asof_now_join(self, other, *on, how=JoinKind.LEFT, **kw)
