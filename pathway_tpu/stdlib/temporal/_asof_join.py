"""Asof joins (parity: reference ``stdlib/temporal/_asof_join.py:479-1000`` and
``_asof_now_join.py:176-332``).

Mechanism: the right side aggregates per join-key into a sorted (time, rowid) tuple; each left
row binary-searches it for the latest-not-after (backward) / earliest-not-before (forward)
match. Incremental via groupby+ix (right updates re-trigger affected left rows).
"""

from __future__ import annotations

import bisect
import enum
from typing import Any, Dict

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.joins import JoinKind
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.table import Table, _name_of
from pathway_tpu.internals import thisclass


class AsofDirection(enum.Enum):
    BACKWARD = "backward"
    FORWARD = "forward"
    NEAREST = "nearest"


Direction = AsofDirection


class AsofJoinResult:
    def __init__(
        self,
        left: Table,
        right: Table,
        left_time: expr.ColumnExpression,
        right_time: expr.ColumnExpression,
        on: tuple,
        kind: JoinKind,
        direction: AsofDirection,
        defaults: Dict[Any, Any] | None = None,
    ):
        self.left = left
        self.right = right
        self.left_time = left_time
        self.right_time = right_time
        self.on = on
        self.kind = kind
        self.direction = direction
        self.defaults = defaults or {}

    def select(self, *args: Any, **kwargs: Any) -> Table:
        left, right = self.left, self.right
        left_on: list[expr.ColumnExpression] = []
        right_on: list[expr.ColumnExpression] = []
        for cond in self.on:
            cond = thisclass.substitute(cond, {thisclass.left: left, thisclass.right: right})
            import operator

            assert (
                isinstance(cond, expr.ColumnBinaryOpExpression)
                and cond._operator is operator.eq
            ), "asof_join conditions must be equalities"
            a, b = cond._left, cond._right
            if any(r.table is left for r in a._column_refs):
                left_on.append(a)
                right_on.append(b)
            else:
                left_on.append(b)
                right_on.append(a)

        rt = right.with_columns(_pw_t=self.right_time)
        # aggregate sorted (time, id) tuples per right key
        rt2 = rt.with_columns(_pw_pair=expr.make_tuple(rt._pw_t, rt.id))
        if right_on:
            rkey = rt2.pointer_from(*[_rebind_to(e, right, rt2) for e in right_on])
            keyed = rt2.with_columns(_pw_key=rkey)
            agg = keyed.groupby(keyed._pw_key).reduce(
                _pw_pairs=reducers.sorted_tuple(keyed._pw_pair)
            )
        else:
            agg = rt2.groupby().reduce(_pw_pairs=reducers.sorted_tuple(rt2._pw_pair))

        lt = left.with_columns(_pw_t=self.left_time)
        if right_on:
            lkey = lt.pointer_from(*[_rebind_to(e, left, lt) for e in left_on])
        else:
            lkey = lt.pointer_from()
        pairs = agg.ix(lkey, optional=True)._pw_pairs

        direction = self.direction

        def pick(mytime: Any, pairs_tuple: Any) -> Any:
            if not pairs_tuple:
                return None
            times = [p[0] for p in pairs_tuple]
            if direction == AsofDirection.BACKWARD:
                i = bisect.bisect_right(times, mytime) - 1
                return pairs_tuple[i][1] if i >= 0 else None
            if direction == AsofDirection.FORWARD:
                i = bisect.bisect_left(times, mytime)
                return pairs_tuple[i][1] if i < len(pairs_tuple) else None
            # nearest
            i = bisect.bisect_left(times, mytime)
            best = None
            for j in (i - 1, i):
                if 0 <= j < len(pairs_tuple):
                    d = abs(times[j] - mytime)
                    if best is None or d < best[0]:
                        best = (d, pairs_tuple[j][1])
            return best[1] if best else None

        match_ptr = expr.apply_with_type(pick, Any, lt._pw_t, pairs)
        with_match = lt.with_columns(_pw_match=match_ptr)
        if self.kind in (JoinKind.INNER,):
            with_match = with_match.filter(with_match._pw_match.is_not_none())
        rmatch = right.ix(with_match._pw_match, optional=True)

        out_exprs: Dict[str, Any] = {}
        for arg in args:
            out_exprs[_name_of(arg)] = arg
        out_exprs.update(kwargs)
        resolved = {}
        for name, e in out_exprs.items():
            e = thisclass.substitute(
                e, {thisclass.left: left, thisclass.right: right, thisclass.this: left}
            )
            resolved[name] = _rebind_pair(e, left, with_match, right, rmatch, self.defaults)
        return with_match.select(**resolved)


def _name_of_expr(e: Any, table: Table) -> str:
    return e.name if isinstance(e, expr.ColumnReference) else str(e)


def _rebind_to(e: Any, old: Table, new: Table) -> Any:
    if isinstance(e, expr.ColumnReference):
        return new[e.name] if e.table is old else e
    if isinstance(e, expr.ColumnExpression):
        import copy

        clone = copy.copy(e)
        for attr, value in list(vars(e).items()):
            if isinstance(value, expr.ColumnExpression):
                setattr(clone, attr, _rebind_to(value, old, new))
            elif isinstance(value, tuple) and any(isinstance(v, expr.ColumnExpression) for v in value):
                setattr(
                    clone,
                    attr,
                    tuple(
                        _rebind_to(v, old, new) if isinstance(v, expr.ColumnExpression) else v
                        for v in value
                    ),
                )
        return clone
    return e


def _rebind_pair(
    e: Any, left: Table, new_left: Table, right: Table, rmatch: Table, defaults: Dict
) -> Any:
    if isinstance(e, expr.ColumnReference):
        if e.table is left:
            return new_left[e.name]
        if e.table is right:
            base = rmatch[e.name]
            if e.name in defaults or e in defaults:
                default = defaults.get(e.name, defaults.get(e))
                return expr.coalesce(base, default)
            return base
        return e
    if isinstance(e, expr.ColumnExpression):
        import copy

        clone = copy.copy(e)
        for attr, value in list(vars(e).items()):
            if isinstance(value, expr.ColumnExpression):
                setattr(clone, attr, _rebind_pair(value, left, new_left, right, rmatch, defaults))
            elif isinstance(value, tuple) and any(isinstance(v, expr.ColumnExpression) for v in value):
                setattr(
                    clone,
                    attr,
                    tuple(
                        _rebind_pair(v, left, new_left, right, rmatch, defaults)
                        if isinstance(v, expr.ColumnExpression)
                        else v
                        for v in value
                    ),
                )
        return clone
    return e


def asof_join(
    self: Table,
    other: Table,
    self_time: Any,
    other_time: Any,
    *on: Any,
    how: JoinKind = JoinKind.LEFT,
    defaults: Dict | None = None,
    direction: AsofDirection = AsofDirection.BACKWARD,
    behavior: Any = None,
) -> AsofJoinResult:
    defaults_by_name = {}
    if defaults:
        for k, v in defaults.items():
            defaults_by_name[k.name if hasattr(k, "name") else k] = v
    return AsofJoinResult(
        self,
        other,
        self._resolve(self_time),
        other._resolve(other_time),
        on,
        how,
        direction,
        defaults_by_name,
    )


def asof_join_inner(self: Table, other: Table, self_time: Any, other_time: Any, *on: Any, **kw: Any) -> AsofJoinResult:
    kw.setdefault("how", JoinKind.INNER)
    return asof_join(self, other, self_time, other_time, *on, **kw)


def asof_join_left(self: Table, other: Table, self_time: Any, other_time: Any, *on: Any, **kw: Any) -> AsofJoinResult:
    kw.setdefault("how", JoinKind.LEFT)
    return asof_join(self, other, self_time, other_time, *on, **kw)


def asof_join_right(self: Table, other: Table, self_time: Any, other_time: Any, *on: Any, **kw: Any) -> AsofJoinResult:
    kw.setdefault("how", JoinKind.RIGHT)
    return asof_join(self, other, self_time, other_time, *on, **kw)


def asof_join_outer(self: Table, other: Table, self_time: Any, other_time: Any, *on: Any, **kw: Any) -> AsofJoinResult:
    kw.setdefault("how", JoinKind.OUTER)
    return asof_join(self, other, self_time, other_time, *on, **kw)


# -- asof_now: query-stream semantics (no retraction of answers) -------------


def asof_now_join(self: Table, other: Table, *on: Any, how: JoinKind = JoinKind.INNER, **kw: Any):
    """Join where ``self`` is a query stream answered as of now (reference
    ``_asof_now_join.py:176``)."""
    from pathway_tpu.stdlib.temporal._interval_join import _rebind

    forgotten = self._forget_immediately()
    # user expressions reference the original left table; rebind them onto the
    # forgetting copy (reference ``_asof_now_join.py:79-84``)
    on = tuple(_rebind(cond, self, forgotten, other, other) for cond in on)
    result = forgotten.join(other, *on, how=how, **kw)
    left_table = self

    class _AsofNowJoinResult:
        def select(self, *args: Any, **kwargs: Any) -> Table:
            args = tuple(
                _rebind(a, left_table, forgotten, other, other) for a in args
            )
            kwargs = {
                k: _rebind(v, left_table, forgotten, other, other)
                for k, v in kwargs.items()
            }
            selected = result.select(*args, **kwargs)
            return selected._filter_out_results_of_forgetting()

    return _AsofNowJoinResult()


def asof_now_join_inner(self: Table, other: Table, *on: Any, **kw: Any):
    return asof_now_join(self, other, *on, how=JoinKind.INNER, **kw)


def asof_now_join_left(self: Table, other: Table, *on: Any, **kw: Any):
    return asof_now_join(self, other, *on, how=JoinKind.LEFT, **kw)
