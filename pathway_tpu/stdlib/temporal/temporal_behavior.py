"""Window behaviors (parity: reference ``stdlib/temporal/temporal_behavior.py:29,83``).

``common_behavior(delay, cutoff, keep_results)`` controls when window results are emitted
(delay = buffer until time advances past start+delay), when late rows are ignored (cutoff),
and whether closed windows keep or forget their results. ``exactly_once_behavior`` is the
delay=cutoff special case. Engine mechanics mirror ``time_column.rs`` (buffer/forget/freeze).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional


class Behavior:
    pass


@dataclass
class CommonBehavior(Behavior):
    delay: Any = None
    cutoff: Any = None
    keep_results: bool = True


@dataclass
class ExactlyOnceBehavior(Behavior):
    shift: Any = None


def common_behavior(delay: Any = None, cutoff: Any = None, keep_results: bool = True) -> CommonBehavior:
    return CommonBehavior(delay, cutoff, keep_results)


def exactly_once_behavior(shift: Any = None) -> ExactlyOnceBehavior:
    return ExactlyOnceBehavior(shift)


def apply_temporal_behavior(
    table: Any, behavior: Optional[CommonBehavior], time_column: str = "_pw_time"
) -> Any:
    """Apply a behavior to a table carrying a time column (reference
    ``temporal_behavior.py:102-113``): delay buffers rows, cutoff freezes late rows and
    forgets old ones."""
    if behavior is None:
        return table
    t = table[time_column]
    if behavior.delay is not None:
        table = table._buffer(t + behavior.delay, t)
        t = table[time_column]
    if behavior.cutoff is not None:
        table = table._freeze(t + behavior.cutoff, t)
        t = table[time_column]
        table = table._forget(t + behavior.cutoff, t, behavior.keep_results)
    return table
