"""Interval joins (parity: reference ``stdlib/temporal/_interval_join.py:577-1404``).

Mechanism: right rows bucket once at ``floor(t/w)``; left rows expand (flatten) to every
bucket their interval ``[t+lo, t+hi]`` can touch, so each matching pair meets in exactly one
bucket — no dedup pass needed. Exact bound check applied as a post-filter.
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass
from typing import Any, Dict, List

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.joins import JoinKind
from pathway_tpu.internals.table import Table, _name_of


@dataclass
class Interval:
    lower_bound: Any
    upper_bound: Any


def interval(lower_bound: Any, upper_bound: Any) -> Interval:
    return Interval(lower_bound, upper_bound)


class IntervalJoinResult:
    def __init__(
        self,
        left: Table,
        right: Table,
        left_time: expr.ColumnExpression,
        right_time: expr.ColumnExpression,
        iv: Interval,
        on: tuple,
        kind: JoinKind,
        behavior: Any = None,
    ):
        self.left = left
        self.right = right
        self.left_time = left_time
        self.right_time = right_time
        self.interval = iv
        self.on = on
        self.kind = kind
        self.behavior = behavior

    def select(self, *args: Any, **kwargs: Any) -> Table:
        lo, hi = self.interval.lower_bound, self.interval.upper_bound
        width = hi - lo
        if _is_zero(width):
            width = _one_like(lo)

        def left_buckets(t: Any) -> tuple:
            start = _bucket_of(t + lo, width)
            end = _bucket_of(t + hi, width)
            out = []
            b = start
            while True:
                out.append(b)
                if b >= end:
                    break
                b += 1
            return tuple(out)

        def right_bucket(t: Any) -> int:
            return _bucket_of(t, width)

        from pathway_tpu.stdlib.temporal.temporal_behavior import (
            apply_temporal_behavior,
        )

        lt = self.left.with_columns(
            _pw_t=self.left_time,
        )
        lt = apply_temporal_behavior(lt, self.behavior, "_pw_t")
        lt = lt.with_columns(
            _pw_buckets=expr.apply_with_type(left_buckets, dt.List_(dt.INT), lt._pw_t)
        )
        lflat = lt.flatten(lt._pw_buckets, origin_id="_pw_left_id")
        rt = self.right.with_columns(_pw_t=self.right_time)
        rt = apply_temporal_behavior(rt, self.behavior, "_pw_t")
        rt = rt.with_columns(
            _pw_bucket=expr.apply_with_type(right_bucket, int, rt._pw_t)
        )

        from pathway_tpu.internals import thisclass

        conditions = [lflat._pw_buckets == rt._pw_bucket]
        for cond in self.on:
            cond = thisclass.substitute(
                cond, {thisclass.left: self.left, thisclass.right: self.right}
            )
            # rebind left refs onto lflat (columns copied by flatten), right onto rt
            cond = _rebind(cond, self.left, lflat, self.right, rt)
            conditions.append(cond)

        joined = lflat.join_inner(rt, *conditions)
        matched = joined.select(
            _pw_left_id=lflat._pw_left_id,
            _pw_right_id=rt.id,
            _pw_lt=lflat._pw_t,
            _pw_rt=rt._pw_t,
        )
        matched = matched.filter(
            (matched._pw_rt - matched._pw_lt >= lo) & (matched._pw_rt - matched._pw_lt <= hi)
        )

        out_exprs: Dict[str, Any] = {}
        for arg in args:
            out_exprs[_name_of(arg)] = arg
        out_exprs.update(kwargs)

        lrows = self.left.ix(matched._pw_left_id)
        rrows = self.right.ix(matched._pw_right_id)
        resolved = {
            name: _rebind_sides(e, self.left, lrows, self.right, rrows)
            for name, e in out_exprs.items()
        }
        inner = matched.select(**resolved)

        if self.kind == JoinKind.INNER:
            return self._post_behavior(inner)
        # outer variants: pad unmatched sides
        parts = [inner]
        if self.kind in (JoinKind.LEFT, JoinKind.OUTER):
            matched_left = matched.groupby(matched._pw_left_id).reduce(
                _pw_id=matched._pw_left_id
            )
            unmatched_left = self._unmatched(self.left, matched_left)
            pad = {
                name: _rebind_sides(e, self.left, unmatched_left, self.right, None)
                for name, e in out_exprs.items()
            }
            parts.append(unmatched_left.select(**pad))
        if self.kind in (JoinKind.RIGHT, JoinKind.OUTER):
            matched_right = matched.groupby(matched._pw_right_id).reduce(
                _pw_id=matched._pw_right_id
            )
            unmatched_right = self._unmatched(self.right, matched_right)
            pad = {
                name: _rebind_sides(e, self.left, None, self.right, unmatched_right)
                for name, e in out_exprs.items()
            }
            parts.append(unmatched_right.select(**pad))
        return self._post_behavior(parts[0].concat_reindex(*parts[1:]))

    def _post_behavior(self, result: Table) -> Table:
        """keep_results=True forgetting must not remove already-delivered join results
        (reference ``_interval_join.py:451``)."""
        b = self.behavior
        if b is not None and b.cutoff is not None and b.keep_results:
            result = result._filter_out_results_of_forgetting()
        return result

    @staticmethod
    def _unmatched(table: Table, matched_ids: Table) -> Table:
        with_flag = table.having(matched_ids._pw_id)
        return table.difference(with_flag)


def _rebind(e: Any, old_left: Table, new_left: Table, old_right: Table, new_right: Table) -> Any:
    if isinstance(e, expr.ColumnReference):
        if e.table is old_left:
            return new_left[e.name]
        if e.table is old_right:
            return new_right[e.name]
        return e
    if isinstance(e, expr.ColumnExpression):
        import copy

        clone = copy.copy(e)
        for attr, value in list(vars(e).items()):
            if isinstance(value, expr.ColumnExpression):
                setattr(clone, attr, _rebind(value, old_left, new_left, old_right, new_right))
            elif isinstance(value, tuple) and any(isinstance(v, expr.ColumnExpression) for v in value):
                setattr(
                    clone,
                    attr,
                    tuple(
                        _rebind(v, old_left, new_left, old_right, new_right)
                        if isinstance(v, expr.ColumnExpression)
                        else v
                        for v in value
                    ),
                )
        return clone
    return e


def _rebind_sides(e: Any, old_left: Table, new_left: Any, old_right: Table, new_right: Any) -> Any:
    if isinstance(e, expr.ColumnReference):
        if e.table is old_left:
            return new_left[e.name] if new_left is not None else expr.ColumnConstExpression(None)
        if e.table is old_right:
            return new_right[e.name] if new_right is not None else expr.ColumnConstExpression(None)
        return e
    if isinstance(e, expr.ColumnExpression):
        import copy

        clone = copy.copy(e)
        for attr, value in list(vars(e).items()):
            if isinstance(value, expr.ColumnExpression):
                setattr(clone, attr, _rebind_sides(value, old_left, new_left, old_right, new_right))
            elif isinstance(value, tuple) and any(isinstance(v, expr.ColumnExpression) for v in value):
                setattr(
                    clone,
                    attr,
                    tuple(
                        _rebind_sides(v, old_left, new_left, old_right, new_right)
                        if isinstance(v, expr.ColumnExpression)
                        else v
                        for v in value
                    ),
                )
        return clone
    return e


def _bucket_of(t: Any, width: Any) -> int:
    if isinstance(t, datetime.datetime):
        epoch = datetime.datetime.min if t.tzinfo is None else datetime.datetime(
            1, 1, 1, tzinfo=datetime.timezone.utc
        )
        return int((t - epoch) // width)
    return int(t // width)


def _is_zero(width: Any) -> bool:
    if isinstance(width, datetime.timedelta):
        return width == datetime.timedelta(0)
    return width == 0


def _one_like(v: Any) -> Any:
    if isinstance(v, datetime.timedelta):
        return datetime.timedelta(seconds=1)
    if isinstance(v, float):
        return 1.0
    return 1


def interval_join(
    self: Table,
    other: Table,
    self_time: Any,
    other_time: Any,
    iv: Interval,
    *on: Any,
    behavior: Any = None,
    how: JoinKind = JoinKind.INNER,
) -> IntervalJoinResult:
    return IntervalJoinResult(
        self,
        other,
        self._resolve(self_time),
        other._resolve(other_time),
        iv,
        on,
        how,
        behavior=behavior,
    )


def interval_join_inner(self: Table, other: Table, self_time: Any, other_time: Any, iv: Interval, *on: Any, **kw: Any) -> IntervalJoinResult:
    return interval_join(self, other, self_time, other_time, iv, *on, how=JoinKind.INNER, **kw)


def interval_join_left(self: Table, other: Table, self_time: Any, other_time: Any, iv: Interval, *on: Any, **kw: Any) -> IntervalJoinResult:
    return interval_join(self, other, self_time, other_time, iv, *on, how=JoinKind.LEFT, **kw)


def interval_join_right(self: Table, other: Table, self_time: Any, other_time: Any, iv: Interval, *on: Any, **kw: Any) -> IntervalJoinResult:
    return interval_join(self, other, self_time, other_time, iv, *on, how=JoinKind.RIGHT, **kw)


def interval_join_outer(self: Table, other: Table, self_time: Any, other_time: Any, iv: Interval, *on: Any, **kw: Any) -> IntervalJoinResult:
    return interval_join(self, other, self_time, other_time, iv, *on, how=JoinKind.OUTER, **kw)
