"""Time utilities (parity: reference ``stdlib/temporal/time_utils.py``)."""

from __future__ import annotations

import datetime
from typing import Any

from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.table import Table


def utc_now(refresh_rate: datetime.timedelta = datetime.timedelta(seconds=60)) -> Table:
    """A single-row table holding the current UTC timestamp, refreshed periodically."""
    import time

    from pathway_tpu.io.python import ConnectorSubject, read
    from pathway_tpu.internals.keys import pointer_from

    class _Clock(ConnectorSubject):
        def run(self) -> None:
            key_row = {"timestamp_utc": None}
            prev = None
            while True:
                now = datetime.datetime.now(datetime.timezone.utc).replace(tzinfo=None)
                if prev is not None:
                    self._emit({"timestamp_utc": prev}, diff=-1)
                self._emit({"timestamp_utc": now}, diff=1)
                prev = now
                time.sleep(refresh_rate.total_seconds())

    schema = sch.schema_from_types(timestamp_utc=datetime.datetime)
    return read(_Clock(), schema=schema)


def inactivity_detection(
    event_time_column: Any,
    allowed_inactivity_period: datetime.timedelta,
    refresh_rate: datetime.timedelta = datetime.timedelta(seconds=1),
    instance: Any = None,
    *,
    now_table: Table | None = None,
) -> tuple:
    """Detect periods of inactivity and activity resumption in an event stream.

    Returns ``(inactivities, resumed_activities)``: tables with ``inactive_t`` (last
    event time before a detected gap) and ``resumed_t`` (first event after a gap).
    Parity: reference ``stdlib/temporal/time_utils.py:171`` — a wall-clock stream
    (:func:`utc_now`) is as-of-now joined against the latest event time per instance;
    gaps longer than ``allowed_inactivity_period`` raise an alert. ``now_table`` lets
    tests inject a deterministic clock stream instead of real wall-clock.
    """
    from pathway_tpu.internals.reducers import reducers

    events_t = event_time_column.table.select(t=event_time_column, instance=instance)

    now_t = now_table if now_table is not None else utc_now(refresh_rate=refresh_rate)
    build_time = datetime.datetime.now(datetime.timezone.utc).replace(tzinfo=None)
    latest_t = events_t.groupby(events_t.instance).reduce(
        events_t.instance, latest_t=reducers.max(events_t.t)
    )
    if now_table is None:
        # avoid alerts while backfilling historical events
        latest_t = latest_t.filter(latest_t.latest_t > build_time)

    joined = now_t.asof_now_join(latest_t).select(
        timestamp_utc=now_t.timestamp_utc,
        instance=latest_t.instance,
        latest_t=latest_t.latest_t,
    )
    stale = joined.filter(
        joined.latest_t + allowed_inactivity_period < joined.timestamp_utc
    )
    inactivities = (
        stale.groupby(stale.latest_t, stale.instance)
        .reduce(stale.latest_t, stale.instance)
    )
    inactivities = inactivities.select(
        instance=inactivities.instance, inactive_t=inactivities.latest_t
    )

    latest_inactivity = inactivities.groupby(inactivities.instance).reduce(
        inactivities.instance, inactive_t=reducers.latest(inactivities.inactive_t)
    )
    ev_joined = events_t.asof_now_join(
        latest_inactivity, events_t.instance == latest_inactivity.instance
    ).select(
        t=events_t.t,
        instance=events_t.instance,
        inactive_t=latest_inactivity.inactive_t,
    )
    after_gap = ev_joined.filter(ev_joined.t > ev_joined.inactive_t)
    resumed_activities = (
        after_gap.groupby(after_gap.inactive_t, after_gap.instance)
        .reduce(after_gap.instance, resumed_t=reducers.min(after_gap.t))
    )
    if instance is None:
        inactivities = inactivities.without("instance")
        resumed_activities = resumed_activities.without("instance")
    return inactivities, resumed_activities
