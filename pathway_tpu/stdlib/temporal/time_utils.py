"""Time utilities (parity: reference ``stdlib/temporal/time_utils.py``)."""

from __future__ import annotations

import datetime
from typing import Any

from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.table import Table


def utc_now(refresh_rate: datetime.timedelta = datetime.timedelta(seconds=60)) -> Table:
    """A single-row table holding the current UTC timestamp, refreshed periodically."""
    import time

    from pathway_tpu.io.python import ConnectorSubject, read
    from pathway_tpu.internals.keys import pointer_from

    class _Clock(ConnectorSubject):
        def run(self) -> None:
            key_row = {"timestamp_utc": None}
            prev = None
            while True:
                now = datetime.datetime.now(datetime.timezone.utc).replace(tzinfo=None)
                if prev is not None:
                    self._emit({"timestamp_utc": prev}, diff=-1)
                self._emit({"timestamp_utc": now}, diff=1)
                prev = now
                time.sleep(refresh_rate.total_seconds())

    schema = sch.schema_from_types(timestamp_utc=datetime.datetime)
    return read(_Clock(), schema=schema)


def inactivity_detection(
    events: Any,
    allowed_inactivity_period: datetime.timedelta,
    refresh_rate: datetime.timedelta = datetime.timedelta(seconds=1),
    instance: Any = None,
) -> tuple:
    """Detect (inactivity_start, resumed) event streams (reference ``time_utils.py``)."""
    raise NotImplementedError(
        "inactivity_detection lands with streaming wall-clock triggers (round 2)"
    )
