"""Windows: tumbling / sliding / session / intervals_over.

Parity: reference ``stdlib/temporal/_window.py:595-865``. Windows desugar onto the core
engine: assign each row its window(s) (≤1 for tumbling, k for sliding via flatten, computed
per-instance for session), then groupby (window, instance). ``_pw_window_start`` /
``_pw_window_end`` / ``_pw_instance`` columns match the reference's naming.
"""

from __future__ import annotations

import datetime
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, Optional

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.table import Table, _name_of
from pathway_tpu.internals import thisclass


def _time_dtype(time_expr: expr.ColumnExpression) -> dt.DType:
    """The window-bound dtype: same as the time column's (window starts/ends
    are arithmetic on time values). Typing these keeps ``_pw_window_start``/
    ``_pw_window_end`` in typed arrays downstream — the engine's columnar fast
    paths only fire when dtypes survive windowing."""
    from pathway_tpu.internals.type_interpreter import eval_type

    return eval_type(time_expr).strip_optional()


class Window(ABC):
    @abstractmethod
    def assign(self, table: Table, time_expr: expr.ColumnExpression) -> Table:
        """Return table extended with _pw_window_start/_pw_window_end (maybe flattened)."""


class TumblingWindow(Window):
    def __init__(self, duration: Any, origin: Any = None, offset: Any = None):
        self.duration = duration
        self.origin = origin if origin is not None else offset

    def assign(self, table: Table, time_expr: expr.ColumnExpression) -> Table:
        duration = self.duration
        origin = self.origin

        def window_start(t: Any) -> Any:
            base = origin if origin is not None else (
                datetime.datetime.min if isinstance(t, datetime.datetime) else 0
            )
            k = (t - base) // duration
            return base + k * duration

        start_e = expr.apply_with_type(window_start, _time_dtype(time_expr), time_expr)
        with_cols = table.with_columns(
            _pw_window_start=start_e,
        )
        return with_cols.with_columns(
            _pw_window_end=with_cols._pw_window_start + duration,
        )


class SlidingWindow(Window):
    def __init__(self, hop: Any, duration: Any = None, ratio: int | None = None, origin: Any = None, offset: Any = None):
        self.hop = hop
        self.duration = duration if duration is not None else hop * (ratio or 1)
        self.origin = origin if origin is not None else offset

    def assign(self, table: Table, time_expr: expr.ColumnExpression) -> Table:
        hop, duration, origin = self.hop, self.duration, self.origin

        def windows_for(t: Any) -> tuple:
            base = origin if origin is not None else (
                datetime.datetime.min if isinstance(t, datetime.datetime) else 0
            )
            # window starts s with s <= t < s + duration and s ≡ base (mod hop)
            out = []
            k = (t - base) // hop
            s = base + k * hop
            while s + duration > t:
                if s <= t:
                    out.append(s)
                s -= hop
            return tuple(sorted(out))

        starts = expr.apply_with_type(
            windows_for, dt.List_(_time_dtype(time_expr)), time_expr
        )
        with_starts = table.with_columns(_pw_window_start=starts)
        flat = with_starts.flatten(with_starts._pw_window_start)
        return flat.with_columns(_pw_window_end=flat._pw_window_start + duration)


class SessionWindow(Window):
    def __init__(self, predicate: Callable | None = None, max_gap: Any = None):
        self.predicate = predicate
        self.max_gap = max_gap

    def assign(self, table: Table, time_expr: expr.ColumnExpression) -> Table:
        # handled specially in windowby (needs per-instance grouping of all rows)
        raise NotImplementedError


class IntervalsOverWindow(Window):
    def __init__(self, at: Any, lower_bound: Any, upper_bound: Any, is_outer: bool = True):
        self.at = at
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.is_outer = is_outer

    def assign(self, table: Table, time_expr: expr.ColumnExpression) -> Table:
        raise NotImplementedError


def tumbling(duration: Any, origin: Any = None, offset: Any = None) -> TumblingWindow:
    return TumblingWindow(duration, origin, offset)


def sliding(hop: Any, duration: Any = None, ratio: int | None = None, origin: Any = None, offset: Any = None) -> SlidingWindow:
    return SlidingWindow(hop, duration, ratio, origin, offset)


def session(*, predicate: Callable | None = None, max_gap: Any = None) -> SessionWindow:
    return SessionWindow(predicate, max_gap)


def intervals_over(*, at: Any, lower_bound: Any, upper_bound: Any, is_outer: bool = True) -> IntervalsOverWindow:
    return IntervalsOverWindow(at, lower_bound, upper_bound, is_outer)


class WindowedTable:
    """Result of ``windowby``; call ``.reduce(...)``."""

    def __init__(
        self,
        assigned: Table,
        instance_name: str | None,
        window: Window,
        shard_cols: Dict[str, str],
        behavior: Any = None,
    ):
        self.assigned = assigned
        self.instance_name = instance_name
        self.window = window
        self.shard_cols = shard_cols  # user column name -> assigned column name
        self.behavior = behavior

    def reduce(self, *args: Any, **kwargs: Any) -> Table:
        t = self.assigned
        grouping = [t._pw_window_start, t._pw_window_end]
        if self.instance_name:
            grouping.append(t[self.instance_name])
        grouped = t.groupby(
            *grouping,
        )
        out_exprs: Dict[str, Any] = {}
        for arg in args:
            out_exprs[_name_of(arg)] = arg
        out_exprs.update(kwargs)
        resolved = {}
        for name, e in out_exprs.items():
            resolved[name] = _rebind_window_refs(e, t, self.instance_name)
        result = grouped.reduce(**resolved)
        from pathway_tpu.stdlib.temporal.temporal_behavior import CommonBehavior

        if (
            isinstance(self.behavior, CommonBehavior)
            and self.behavior.cutoff is not None
            and self.behavior.keep_results
        ):
            # forgetting retractions (neu times) must not remove delivered window results
            result = result._filter_out_results_of_forgetting()
        if isinstance(self.window, IntervalsOverWindow) and self.window.is_outer:
            result = self._add_empty_windows(result, resolved)
        return result

    def _add_empty_windows(self, result: Table, resolved: Dict[str, Any]) -> Table:
        """Outer intervals_over: every ``at`` point yields a window even with no rows
        (reference ``_window.py:831``); reducer columns are None for empty windows."""
        if self.instance_name:
            return result  # instance-grouped outer windows not yet supported
        at_col = self.window.at  # type: ignore[attr-defined]
        ats = at_col.table.groupby(at_col).reduce(_pw_at=at_col)
        win = ats.select(_pw_window_start=ats._pw_at, _pw_window_end=ats._pw_at)
        win = win.with_id(win.pointer_from(win._pw_window_start, win._pw_window_end))
        null_exprs: Dict[str, Any] = {}
        for name, e in resolved.items():
            null_exprs[name] = _empty_window_value(e, win)
        empty_rows = win.select(**null_exprs)
        return empty_rows.update_rows(result)


def _empty_window_value(e: Any, win: Table) -> Any:
    """Value of a reduce output expression over an empty window: window-bound refs map to
    the ``at`` point's window columns, anything involving data reducers becomes None."""
    if isinstance(e, expr.ColumnReference):
        if e.name in ("_pw_window_start", "_pw_window_end"):
            return win[e.name]
        return expr.ColumnConstExpression(None)
    if isinstance(e, expr.MakeTupleExpression):
        parts = [_empty_window_value(v, win) for v in e._args]
        if all(
            isinstance(p, (expr.ColumnReference, expr.ColumnConstExpression)) for p in parts
        ):
            return expr.make_tuple(*parts)
    return expr.ColumnConstExpression(None)


def _rebind_window_refs(e: Any, t: Table, instance_name: str | None) -> Any:
    """Map pw.this refs onto the assigned table, incl. _pw_window* virtual columns."""
    if isinstance(e, thisclass.ThisColumnReference):
        name = e.name
        if name == "_pw_window":
            return expr.make_tuple(t._pw_window_start, t._pw_window_end)
        if name == "_pw_instance":
            return t[instance_name] if instance_name else expr.ColumnConstExpression(None)
        return t[name]
    if isinstance(e, expr.ColumnReference):
        if e.name in ("_pw_window_start", "_pw_window_end") and e.table is not t:
            return t[e.name]
        return e
    if isinstance(e, expr.ColumnExpression):
        import copy

        clone = copy.copy(e)
        for attr, value in list(vars(e).items()):
            if isinstance(value, expr.ColumnExpression):
                setattr(clone, attr, _rebind_window_refs(value, t, instance_name))
            elif isinstance(value, tuple) and any(
                isinstance(v, expr.ColumnExpression) for v in value
            ):
                setattr(
                    clone,
                    attr,
                    tuple(
                        _rebind_window_refs(v, t, instance_name)
                        if isinstance(v, expr.ColumnExpression)
                        else v
                        for v in value
                    ),
                )
        return clone
    return e


def windowby(
    table: Table,
    time_expr: Any,
    *,
    window: Window,
    behavior: Any = None,
    instance: Any = None,
    **kwargs: Any,
) -> WindowedTable:
    time_e = table._resolve(time_expr)
    instance_name = None
    if instance is not None:
        instance_name = _name_of(instance)

    if isinstance(window, SessionWindow):
        assigned = _assign_sessions(table, time_e, window, instance_name)
    elif isinstance(window, IntervalsOverWindow):
        assigned = _assign_intervals_over(table, time_e, window, instance_name)
    else:
        with_time = table.with_columns(_pw_time=time_e)
        assigned = window.assign(with_time, with_time._pw_time)
    behavior = _canonical_behavior(behavior, window)
    if behavior is not None:
        assigned = _apply_behavior(assigned, behavior)
    return WindowedTable(assigned, instance_name, window, {}, behavior=behavior)


def _assign_sessions(
    table: Table, time_e: expr.ColumnExpression, window: SessionWindow, instance_name: str | None
) -> Table:
    """Compute per-instance session membership via a grouped sorted-tuple + row-wise lookup."""
    max_gap = window.max_gap
    predicate = window.predicate

    t = table.with_columns(_pw_time=time_e)
    if instance_name:
        # grouped-by-instance id is pointer_from(instance), so rows can ix into it
        agg = t.groupby(t[instance_name]).reduce(
            t[instance_name], _pw_times=reducers.sorted_tuple(t._pw_time)
        )
        lookup = t.select(
            _pw_times=agg.ix(t.pointer_from(t[instance_name]))._pw_times
        )
        times_col = lookup._pw_times
    else:
        agg = t.groupby().reduce(_pw_times=reducers.sorted_tuple(t._pw_time))
        lookup = t.select(_pw_times=agg.ix(t.pointer_from())._pw_times)
        times_col = lookup._pw_times

    def session_bounds(mytime: Any, times: tuple) -> tuple:
        # split sorted times into sessions by gap / predicate; find mine
        sessions: list[list] = []
        for v in times:
            if not sessions:
                sessions.append([v])
                continue
            prev = sessions[-1][-1]
            joined = (
                predicate(prev, v)
                if predicate is not None
                else (v - prev) <= max_gap
            )
            if joined:
                sessions[-1].append(v)
            else:
                sessions.append([v])
        for s in sessions:
            if s[0] <= mytime <= s[-1] and mytime in s:
                return (s[0], s[-1])
        return (mytime, mytime)

    td = _time_dtype(time_e)
    bounds = expr.apply_with_type(
        session_bounds, dt.Tuple_(td, td), t._pw_time, times_col
    )
    with_bounds = t.with_columns(_pw_session=bounds)
    return with_bounds.with_columns(
        _pw_window_start=with_bounds._pw_session[0],
        _pw_window_end=with_bounds._pw_session[1],
    ).without("_pw_session")


def _assign_intervals_over(
    table: Table, time_e: expr.ColumnExpression, window: IntervalsOverWindow, instance_name: str | None
) -> Table:
    """Each ``at`` point defines a window [at+lower, at+upper]; rows join all containing."""
    at_column = window.at
    at_table = at_column.table
    lower, upper = window.lower_bound, window.upper_bound
    ats = at_table.groupby(at_column).reduce(_pw_at=at_column)
    collected = ats.groupby().reduce(_pw_all_ats=reducers.sorted_tuple(ats._pw_at))
    t = table.with_columns(_pw_time=time_e)
    with_ats = t.select(
        _pw_ats_tuple=collected.ix(t.pointer_from())._pw_all_ats,
    )

    def matching_ats(mytime: Any, all_ats: tuple) -> tuple:
        return tuple(a for a in all_ats if a + lower <= mytime <= a + upper)

    matched = t.with_columns(
        _pw_window_start=expr.apply_with_type(
            matching_ats,
            dt.List_(_time_dtype(time_e)),
            t._pw_time,
            with_ats._pw_ats_tuple,
        )
    )
    flat = matched.flatten(matched._pw_window_start)
    return flat.with_columns(
        _pw_window_end=flat._pw_window_start,
    )


def _canonical_behavior(behavior: Any, window: Window) -> Any:
    """ExactlyOnceBehavior desugars to common_behavior(duration+shift, shift, True) as in
    the reference (``_window.py:373-389``)."""
    from pathway_tpu.stdlib.temporal.temporal_behavior import (
        CommonBehavior,
        ExactlyOnceBehavior,
        common_behavior,
    )

    if not isinstance(behavior, ExactlyOnceBehavior):
        return behavior
    duration = getattr(window, "duration", None)
    if duration is None:
        raise ValueError("exactly_once_behavior requires a tumbling/sliding window")
    shift = behavior.shift
    if shift is None:
        shift = (
            datetime.timedelta(0) if isinstance(duration, datetime.timedelta) else 0
        )
    return common_behavior(duration + shift, shift, True)


def _apply_behavior(assigned: Table, behavior: Any) -> Table:
    """Wire behavior onto the assigned rows via the engine's time-threshold operators,
    in the reference's order (``_window.py:395-414``): freeze late rows past the cutoff,
    buffer emission until window_start+delay, forget rows past the cutoff."""
    from pathway_tpu.stdlib.temporal.temporal_behavior import CommonBehavior

    if not isinstance(behavior, CommonBehavior):
        raise ValueError(f"unsupported window behavior: {behavior!r}")
    t = assigned
    if behavior.cutoff is not None:
        t = t._freeze(t._pw_window_end + behavior.cutoff, t._pw_time)
    if behavior.delay is not None:
        t = t._buffer(t._pw_window_start + behavior.delay, t._pw_time)
    if behavior.cutoff is not None:
        t = t._forget(
            t._pw_window_end + behavior.cutoff, t._pw_time, behavior.keep_results
        )
    return t
