"""Temporal stdlib: windows, interval joins, asof joins, behaviors.

Parity: reference ``stdlib/temporal/`` — ``windowby`` + session/sliding/tumbling windows
(``_window.py:595-865``), ``interval_join*`` (``_interval_join.py``), ``asof_join*``
(``_asof_join.py``), ``asof_now_join*``, ``window_join*``, behaviors
(``temporal_behavior.py:29,83``). Mechanism: windows desugar to flatten+groupby over computed
window keys (batch-incremental); interval joins use the two-bucket expansion trick so each
matching pair joins exactly once; asof joins aggregate the right side into per-key sorted
tuples and binary-search row-wise.
"""

from pathway_tpu.stdlib.temporal._window import (
    Window,
    intervals_over,
    session,
    sliding,
    tumbling,
    windowby,
)
from pathway_tpu.stdlib.temporal._interval_join import (
    interval,
    interval_join,
    interval_join_inner,
    interval_join_left,
    interval_join_outer,
    interval_join_right,
)
from pathway_tpu.stdlib.temporal._asof_join import (
    AsofDirection,
    Direction,
    asof_join,
    asof_join_inner,
    asof_join_left,
    asof_join_outer,
    asof_join_right,
    asof_now_join,
    asof_now_join_inner,
    asof_now_join_left,
)
from pathway_tpu.stdlib.temporal._window_join import (
    window_join,
    window_join_inner,
    window_join_left,
    window_join_outer,
    window_join_right,
)
from pathway_tpu.stdlib.temporal.temporal_behavior import (
    Behavior,
    CommonBehavior,
    ExactlyOnceBehavior,
    apply_temporal_behavior,
    common_behavior,
    exactly_once_behavior,
)
from pathway_tpu.stdlib.temporal.time_utils import inactivity_detection, utc_now

__all__ = [
    "AsofDirection",
    "Direction",
    "Behavior",
    "CommonBehavior",
    "ExactlyOnceBehavior",
    "Window",
    "asof_join",
    "asof_join_inner",
    "asof_join_left",
    "asof_join_outer",
    "asof_join_right",
    "asof_now_join",
    "asof_now_join_inner",
    "asof_now_join_left",
    "apply_temporal_behavior",
    "common_behavior",
    "exactly_once_behavior",
    "inactivity_detection",
    "interval",
    "interval_join",
    "interval_join_inner",
    "interval_join_left",
    "interval_join_outer",
    "interval_join_right",
    "intervals_over",
    "session",
    "sliding",
    "tumbling",
    "utc_now",
    "window_join",
    "window_join_inner",
    "window_join_left",
    "window_join_outer",
    "window_join_right",
    "windowby",
]
