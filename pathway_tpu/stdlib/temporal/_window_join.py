"""Window joins (parity: reference ``stdlib/temporal/_window_join.py:156-996``).

A window join is an interval/equality join on window membership: both sides assign windows,
then join on (window, *on).
"""

from __future__ import annotations

from typing import Any, Dict

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.joins import JoinKind
from pathway_tpu.internals.table import Table, _name_of
from pathway_tpu.internals import thisclass
from pathway_tpu.stdlib.temporal._window import Window


class WindowJoinResult:
    def __init__(
        self,
        left: Table,
        right: Table,
        left_time: expr.ColumnExpression,
        right_time: expr.ColumnExpression,
        window: Window,
        on: tuple,
        kind: JoinKind,
    ):
        self.left = left
        self.right = right
        self.left_time = left_time
        self.right_time = right_time
        self.window = window
        self.on = on
        self.kind = kind

    def select(self, *args: Any, **kwargs: Any) -> Table:
        from pathway_tpu.stdlib.temporal._window import SessionWindow

        if isinstance(self.window, SessionWindow):
            return self._select_session(*args, **kwargs)
        lt = self.window.assign(self.left, self.left_time)
        rt = self.window.assign(self.right, self.right_time)

        conditions = [
            lt._pw_window_start == rt._pw_window_start,
            lt._pw_window_end == rt._pw_window_end,
        ]
        for cond in self.on:
            cond = thisclass.substitute(
                cond, {thisclass.left: self.left, thisclass.right: self.right}
            )
            conditions.append(_rebind2(cond, self.left, lt, self.right, rt))

        joined = self._join(lt, rt, conditions)

        out_exprs: Dict[str, Any] = {}
        for arg in args:
            out_exprs[_name_of(arg)] = arg
        out_exprs.update(kwargs)
        resolved = {}
        for name, e in out_exprs.items():
            # window virtual columns resolve before this/left/right substitution
            # (pw.this._pw_window_start has no table to substitute onto); outer
            # modes take whichever side is present
            if isinstance(e, thisclass.ThisColumnReference) and e.name in (
                "_pw_window",
                "_pw_window_start",
                "_pw_window_end",
            ):
                if e.name == "_pw_window":
                    from pathway_tpu.internals import expression as e_mod

                    e2 = e_mod.make_tuple(
                        expr.coalesce(lt._pw_window_start, rt._pw_window_start),
                        expr.coalesce(lt._pw_window_end, rt._pw_window_end),
                    )
                else:
                    e2 = expr.coalesce(lt[e.name], rt[e.name])
                resolved[name] = e2
                continue
            e = thisclass.substitute(
                e, {thisclass.left: self.left, thisclass.right: self.right}
            )
            resolved[name] = _rebind2(e, self.left, lt, self.right, rt)
        return joined.select(**resolved)

    def _join(self, lt: Table, rt: Table, conditions: list) -> Any:
        return lt.join(rt, *conditions, how=self.kind)

    def _select_session(self, *args: Any, **kwargs: Any) -> Table:
        """Session windows form over the CONCATENATION of both sides (per join key):
        a left and a right record sharing one session join (reference
        ``_window_join.py:174-179``). Mechanism: a slim union table (time, key,
        side, origin id) is session-assigned per key; sides re-split and join on
        (session, key); original columns resolve through ``ix`` on the origin ids
        so outer modes pad naturally."""
        import operator

        from pathway_tpu.internals import expression as e_mod
        from pathway_tpu.stdlib.temporal._window import _assign_sessions

        left, right = self.left, self.right
        left_on: list = []
        right_on: list = []
        for cond in self.on:
            cond = thisclass.substitute(
                cond, {thisclass.left: left, thisclass.right: right}
            )
            assert (
                isinstance(cond, expr.ColumnBinaryOpExpression)
                and cond._operator is operator.eq
            ), "session window_join conditions must be equalities"
            a, b = cond._left, cond._right
            if any(r.table is left for r in a._column_refs):
                left_on.append(a)
                right_on.append(b)
            else:
                left_on.append(b)
                right_on.append(a)

        def slim(table: Table, time_e: Any, keys: list, side: bool) -> Table:
            return table.select(
                _pw_t=time_e,
                _pw_orig=table.id,
                _pw_side=e_mod.ColumnConstExpression(side),
                _pw_inst=e_mod.make_tuple(*keys) if keys else e_mod.ColumnConstExpression(0),
            )

        lt0 = slim(left, self.left_time, left_on, False)
        rt0 = slim(right, self.right_time, right_on, True)
        union = lt0.concat_reindex(rt0)
        assigned = _assign_sessions(union, union._pw_t, self.window, "_pw_inst")
        ls = assigned.filter(~assigned._pw_side)
        rs = assigned.filter(assigned._pw_side)
        joined = ls.join(
            rs,
            ls._pw_window_start == rs._pw_window_start,
            ls._pw_window_end == rs._pw_window_end,
            ls._pw_inst == rs._pw_inst,
            how=self.kind,
        )
        m = joined.select(
            _pw_l=ls._pw_orig,
            _pw_r=rs._pw_orig,
            _pw_ws=expr.coalesce(ls._pw_window_start, rs._pw_window_start),
            _pw_we=expr.coalesce(ls._pw_window_end, rs._pw_window_end),
        )
        lrows = left.ix(m._pw_l, optional=True)
        rrows = right.ix(m._pw_r, optional=True)

        out_exprs: Dict[str, Any] = {}
        for arg in args:
            out_exprs[_name_of(arg)] = arg
        out_exprs.update(kwargs)
        resolved = {}
        for name, e in out_exprs.items():
            e = thisclass.substitute(
                e, {thisclass.left: left, thisclass.right: right}
            )
            if isinstance(e, expr.ColumnReference) and e.name in (
                "_pw_window",
                "_pw_window_start",
                "_pw_window_end",
            ):
                resolved[name] = (
                    e_mod.make_tuple(m._pw_ws, m._pw_we) if e.name == "_pw_window"
                    else (m._pw_ws if e.name == "_pw_window_start" else m._pw_we)
                )
                continue
            resolved[name] = _rebind2(e, left, lrows, right, rrows)
        return m.select(**resolved)


def _rebind2(e: Any, old_left: Table, new_left: Table, old_right: Table, new_right: Table) -> Any:
    if isinstance(e, expr.ColumnReference):
        if e.table is old_left:
            return new_left[e.name]
        if e.table is old_right:
            return new_right[e.name]
        return e
    if isinstance(e, expr.ColumnExpression):
        import copy

        clone = copy.copy(e)
        for attr, value in list(vars(e).items()):
            if isinstance(value, expr.ColumnExpression):
                setattr(clone, attr, _rebind2(value, old_left, new_left, old_right, new_right))
            elif isinstance(value, tuple) and any(isinstance(v, expr.ColumnExpression) for v in value):
                setattr(
                    clone,
                    attr,
                    tuple(
                        _rebind2(v, old_left, new_left, old_right, new_right)
                        if isinstance(v, expr.ColumnExpression)
                        else v
                        for v in value
                    ),
                )
        return clone
    return e


def window_join(
    self: Table,
    other: Table,
    self_time: Any,
    other_time: Any,
    window: Window,
    *on: Any,
    how: JoinKind = JoinKind.INNER,
) -> WindowJoinResult:
    return WindowJoinResult(
        self, other, self._resolve(self_time), other._resolve(other_time), window, on, how
    )


def window_join_inner(self: Table, other: Table, self_time: Any, other_time: Any, window: Window, *on: Any) -> WindowJoinResult:
    return window_join(self, other, self_time, other_time, window, *on, how=JoinKind.INNER)


def window_join_left(self: Table, other: Table, self_time: Any, other_time: Any, window: Window, *on: Any) -> WindowJoinResult:
    return window_join(self, other, self_time, other_time, window, *on, how=JoinKind.LEFT)


def window_join_right(self: Table, other: Table, self_time: Any, other_time: Any, window: Window, *on: Any) -> WindowJoinResult:
    return window_join(self, other, self_time, other_time, window, *on, how=JoinKind.RIGHT)


def window_join_outer(self: Table, other: Table, self_time: Any, other_time: Any, window: Window, *on: Any) -> WindowJoinResult:
    return window_join(self, other, self_time, other_time, window, *on, how=JoinKind.OUTER)
