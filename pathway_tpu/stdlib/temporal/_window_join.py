"""Window joins (parity: reference ``stdlib/temporal/_window_join.py:156-996``).

A window join is an interval/equality join on window membership: both sides assign windows,
then join on (window, *on).
"""

from __future__ import annotations

from typing import Any, Dict

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.joins import JoinKind
from pathway_tpu.internals.table import Table, _name_of
from pathway_tpu.internals import thisclass
from pathway_tpu.stdlib.temporal._window import Window


class WindowJoinResult:
    def __init__(
        self,
        left: Table,
        right: Table,
        left_time: expr.ColumnExpression,
        right_time: expr.ColumnExpression,
        window: Window,
        on: tuple,
        kind: JoinKind,
    ):
        self.left = left
        self.right = right
        self.left_time = left_time
        self.right_time = right_time
        self.window = window
        self.on = on
        self.kind = kind

    def select(self, *args: Any, **kwargs: Any) -> Table:
        lt = self.window.assign(self.left, self.left_time)
        rt = self.window.assign(self.right, self.right_time)

        conditions = [
            lt._pw_window_start == rt._pw_window_start,
            lt._pw_window_end == rt._pw_window_end,
        ]
        for cond in self.on:
            cond = thisclass.substitute(
                cond, {thisclass.left: self.left, thisclass.right: self.right}
            )
            conditions.append(_rebind2(cond, self.left, lt, self.right, rt))

        joined = self._join(lt, rt, conditions)

        out_exprs: Dict[str, Any] = {}
        for arg in args:
            out_exprs[_name_of(arg)] = arg
        out_exprs.update(kwargs)
        resolved = {}
        for name, e in out_exprs.items():
            e = thisclass.substitute(
                e, {thisclass.left: self.left, thisclass.right: self.right}
            )
            if isinstance(e, thisclass.ThisColumnReference) and e.name in (
                "_pw_window",
                "_pw_window_start",
                "_pw_window_end",
            ):
                e = lt[e.name]
            resolved[name] = _rebind2(e, self.left, lt, self.right, rt)
        return joined.select(**resolved)

    def _join(self, lt: Table, rt: Table, conditions: list) -> Any:
        return lt.join(rt, *conditions, how=self.kind)


def _rebind2(e: Any, old_left: Table, new_left: Table, old_right: Table, new_right: Table) -> Any:
    if isinstance(e, expr.ColumnReference):
        if e.table is old_left:
            return new_left[e.name]
        if e.table is old_right:
            return new_right[e.name]
        return e
    if isinstance(e, expr.ColumnExpression):
        import copy

        clone = copy.copy(e)
        for attr, value in list(vars(e).items()):
            if isinstance(value, expr.ColumnExpression):
                setattr(clone, attr, _rebind2(value, old_left, new_left, old_right, new_right))
            elif isinstance(value, tuple) and any(isinstance(v, expr.ColumnExpression) for v in value):
                setattr(
                    clone,
                    attr,
                    tuple(
                        _rebind2(v, old_left, new_left, old_right, new_right)
                        if isinstance(v, expr.ColumnExpression)
                        else v
                        for v in value
                    ),
                )
        return clone
    return e


def window_join(
    self: Table,
    other: Table,
    self_time: Any,
    other_time: Any,
    window: Window,
    *on: Any,
    how: JoinKind = JoinKind.INNER,
) -> WindowJoinResult:
    return WindowJoinResult(
        self, other, self._resolve(self_time), other._resolve(other_time), window, on, how
    )


def window_join_inner(self: Table, other: Table, self_time: Any, other_time: Any, window: Window, *on: Any) -> WindowJoinResult:
    return window_join(self, other, self_time, other_time, window, *on, how=JoinKind.INNER)


def window_join_left(self: Table, other: Table, self_time: Any, other_time: Any, window: Window, *on: Any) -> WindowJoinResult:
    return window_join(self, other, self_time, other_time, window, *on, how=JoinKind.LEFT)


def window_join_right(self: Table, other: Table, self_time: Any, other_time: Any, window: Window, *on: Any) -> WindowJoinResult:
    return window_join(self, other, self_time, other_time, window, *on, how=JoinKind.RIGHT)


def window_join_outer(self: Table, other: Table, self_time: Any, other_time: Any, window: Window, *on: Any) -> WindowJoinResult:
    return window_join(self, other, self_time, other_time, window, *on, how=JoinKind.OUTER)
