"""AsyncTransformer — fully-async row transformer with a loop-back connector.

Parity: reference ``stdlib/utils/async_transformer.py`` (``_AsyncConnector:61-527``).
Each input row is handed to ``async def invoke(self, **row)`` on a dedicated worker
event loop; results re-enter the graph through a loop-back streaming source as the
``output_table`` (keyed by the INPUT row's key, upsert semantics), so invocations never
block the commit that carried their inputs. Statuses mirror the reference:
``successful`` (rows whose invoke returned), ``failed`` (rows that raised — and, with
``instance`` grouping, successful rows of an instance-time group in which ANY row
failed), ``finished``, ``output_table``. Instance consistency: an (instance, time)
group's results are released atomically, in time order per instance, only when every
invocation of the group completed. ``with_options`` applies capacity / timeout /
retry / cache around ``invoke`` (``internals/udfs`` strategies).
"""

from __future__ import annotations

import asyncio
import collections
import threading
from dataclasses import dataclass, field
from typing import Any, ClassVar, Dict, Optional

from pathway_tpu.engine.datasource import StreamingDataSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table

_ASYNC_STATUS_COLUMN = "_async_status"
_SUCCESS = "-SUCCESS-"
_FAILURE = "-FAILURE-"
_INSTANCE_NAME = "_pw_instance"


@dataclass(frozen=True)
class _Entry:
    key: Any
    time: int
    seq: int
    is_addition: bool


@dataclass
class _Instance:
    pending: collections.deque = field(default_factory=collections.deque)
    finished: Dict[_Entry, Any] = field(default_factory=dict)
    buffer: list = field(default_factory=list)
    buffer_time: Optional[int] = None
    correct: bool = True


class AsyncTransformer:
    """Subclass with ``output_schema`` (class kwarg or attribute) and
    ``async def invoke(self, **row) -> dict``."""

    output_schema: ClassVar[Any] = None

    def __init_subclass__(cls, /, output_schema: Any = None, **kwargs: Any):
        super().__init_subclass__(**kwargs)
        if output_schema is not None:
            cls.output_schema = output_schema

    def __init__(
        self,
        input_table: Table,
        *,
        instance: Any = None,
        autocommit_duration_ms: int | None = 100,
        **kwargs: Any,
    ):
        assert self.output_schema is not None, "define output_schema"
        self._input_table = input_table
        self._instance_expr = instance  # None -> per-row instance (the row key)
        self._autocommit_ms = autocommit_duration_ms
        self._options: Dict[str, Any] = {}
        self._built: Optional[Table] = None

    async def invoke(self, **kwargs: Any) -> Dict[str, Any]:  # pragma: no cover
        raise NotImplementedError

    def open(self) -> None:
        pass

    def close(self) -> None:
        pass

    def with_options(
        self,
        capacity: int | None = None,
        timeout: float | None = None,
        retry_strategy: Any = None,
        cache_strategy: Any = None,
    ) -> "AsyncTransformer":
        self._options = {
            "capacity": capacity,
            "timeout": timeout,
            "retry_strategy": retry_strategy,
            "cache_strategy": cache_strategy,
        }
        return self

    # -- result tables -------------------------------------------------------

    @property
    def output_table(self) -> Table:
        """All rows that finished execution, with ``_async_status``."""
        if self._built is None:
            self._built = self._build()
        return self._built

    @property
    def successful(self) -> Table:
        out = self.output_table
        result = out.filter(out[_ASYNC_STATUS_COLUMN] == _SUCCESS).without(
            _ASYNC_STATUS_COLUMN
        )
        result._schema = self.output_schema
        return result

    @property
    def failed(self) -> Table:
        out = self.output_table
        return out.filter(out[_ASYNC_STATUS_COLUMN] == _FAILURE).without(
            _ASYNC_STATUS_COLUMN
        )

    @property
    def finished(self) -> Table:
        return self.output_table

    @property
    def result(self) -> Table:
        return self.successful

    # -- machinery -----------------------------------------------------------

    def _apply_options(self, fn: Any) -> Any:
        """Wrap invoke with the shared async UDF composition
        (``internals/udfs.wrap_async``: capacity/timeout/retries/caching)."""
        if not any(v is not None for v in self._options.values()):
            return fn
        from pathway_tpu.internals.udfs import wrap_async

        return wrap_async(
            fn,
            capacity=self._options.get("capacity"),
            timeout=self._options.get("timeout"),
            retry_strategy=self._options.get("retry_strategy"),
            cache_strategy=self._options.get("cache_strategy"),
            name=type(self).__name__,
        )

    def _build(self) -> Table:
        from pathway_tpu.internals import expression as expr
        from pathway_tpu.io._subscribe import subscribe

        input_table = self._input_table
        if self._instance_expr is not None:
            inst_e = self._instance_expr
            if not isinstance(inst_e, expr.ColumnExpression):
                inst_e = expr.ColumnConstExpression(inst_e)
            input_table = input_table.with_columns(**{_INSTANCE_NAME: inst_e})
        names = [
            n for n in input_table.column_names() if n != _INSTANCE_NAME
        ]
        out_names = list(self.output_schema.column_names())
        self.open()
        invoke = self._apply_options(self.invoke)

        source = StreamingDataSource(autocommit_ms=self._autocommit_ms, loopback=True)
        state: Dict[bytes, dict] = {}  # key bytes -> last emitted row (upserts)

        loop = asyncio.new_event_loop()
        threading.Thread(
            target=loop.run_forever, daemon=True, name="pathway:async-transformer"
        ).start()
        instances: Dict[Any, _Instance] = {}
        inflight: set = set()
        seq_box = [0]
        ended = [False]
        closed_time = [-1]  # flushes gate on time-end markers (reference semantics)

        def upsert(key: Any, row: dict, status: str) -> None:
            data = {**row, _ASYNC_STATUS_COLUMN: status}
            kb = repr(key).encode()
            old = state.pop(kb, None)
            if old is not None:
                source.push(old, key=key, diff=-1)
            source.push(data, key=key, diff=1)
            state[kb] = data

        def remove(key: Any) -> None:
            old = state.pop(repr(key).encode(), None)
            if old is not None:
                source.push(old, key=key, diff=-1)

        def flush_buffer(inst: _Instance) -> None:
            for key, is_addition, result in inst.buffer:
                if is_addition and inst.correct:
                    upsert(key, result, _SUCCESS)
                elif is_addition:
                    # instance consistency: one failure poisons the whole
                    # (instance, time) group (reference .failed contract)
                    upsert(key, {n: None for n in out_names}, _FAILURE)
                else:
                    remove(key)
            inst.buffer.clear()

        def maybe_produce(instance_key: Any) -> None:
            inst = instances.get(instance_key)
            if inst is None:
                return
            while inst.pending:
                entry = inst.pending[0]
                if entry.time > closed_time[0] or entry not in inst.finished:
                    # the entry's commit is still delivering (its time is not
                    # closed) or its invocation is still running
                    break
                inst.pending.popleft()
                result = inst.finished.pop(entry)
                if inst.buffer_time != entry.time:
                    if inst.buffer:
                        flush_buffer(inst)
                        inst.correct = True
                    inst.buffer_time = entry.time
                if entry.is_addition:
                    if result is None:
                        inst.correct = False
                    inst.buffer.append((entry.key, True, result))
                else:
                    inst.buffer.append((entry.key, False, None))
            if not inst.pending:
                flush_buffer(inst)
                del instances[instance_key]
            elif inst.buffer and inst.pending[0].time != inst.buffer_time:
                # the (instance, time) group completed even though later times wait
                flush_buffer(inst)
                inst.correct = True

        def maybe_close() -> None:
            if ended[0] and not inflight and not instances:
                self.close()
                source.close()

        def task_done(instance_key: Any, entry: _Entry, result: Any) -> None:
            inflight.discard(entry)
            inst = instances.get(instance_key)
            if inst is not None:
                inst.finished[entry] = result
            maybe_produce(instance_key)
            maybe_close()

        def on_change(key: Any, row: dict, time: int, is_addition: bool) -> None:
            # registration AND completion both run on the worker loop thread, in
            # arrival order: a fast task can never flush its (instance, time)
            # group before a sibling entry registered
            instance_key = row.get(_INSTANCE_NAME, key) if self._instance_expr is not None else key
            seq_box[0] += 1
            entry = _Entry(key, time, seq_box[0], is_addition)
            values = {n: row[n] for n in names} if is_addition else None

            def register_and_spawn() -> None:
                instances.setdefault(instance_key, _Instance()).pending.append(entry)
                inflight.add(entry)
                if not is_addition:
                    task_done(instance_key, entry, None)
                    return

                async def run_one() -> None:
                    try:
                        result = await invoke(**values)
                        if set(result.keys()) != set(out_names):
                            raise ValueError(
                                "result of async function does not match output_schema"
                            )
                    except Exception:
                        result = None
                    task_done(instance_key, entry, result)

                loop.create_task(run_one())

            loop.call_soon_threadsafe(register_and_spawn)

        def on_time_end(time: int) -> None:
            def mark() -> None:
                closed_time[0] = max(closed_time[0], time)
                for instance_key in list(instances):
                    maybe_produce(instance_key)
                maybe_close()

            loop.call_soon_threadsafe(mark)

        def on_end() -> None:
            def finish() -> None:
                ended[0] = True
                maybe_close()

            loop.call_soon_threadsafe(finish)

        subscribe(input_table, on_change=on_change, on_end=on_end, on_time_end=on_time_end)

        out_schema = sch.schema_from_columns(
            {
                **{
                    n: sch.ColumnSchema(n, dt.Optional_(c.dtype))
                    for n, c in self.output_schema.columns().items()
                },
                _ASYNC_STATUS_COLUMN: sch.ColumnSchema(_ASYNC_STATUS_COLUMN, dt.STR),
            },
            name="async_transformer",
        )
        node = G.add_node(
            pg.InputNode(source=source, streaming=True, name="async-transformer")
        )
        return Table(node, out_schema, name="async_transformer")
