"""AsyncTransformer — fully-async row transformer with loop-back connector.

Parity: reference ``stdlib/utils/async_transformer.py`` (``_AsyncConnector:61``): each input
row is handed to an async ``invoke``; results stream back into the graph as a new table,
preserving instance consistency.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Dict

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


class AsyncTransformer:
    """Subclass, define ``output_schema`` and ``async def invoke(self, **row) -> dict``."""

    output_schema: sch.SchemaMetaclass

    def __init__(self, input_table: Table, instance: Any = None, **kwargs: Any):
        self._input_table = input_table
        self._instance = instance

    async def invoke(self, **kwargs: Any) -> Dict[str, Any]:  # pragma: no cover
        raise NotImplementedError

    def open(self) -> None:
        pass

    def close(self) -> None:
        pass

    @property
    def successful(self) -> Table:
        return self.result

    @property
    def result(self) -> Table:
        if not hasattr(self, "_result"):
            self._result = self._build()
        return self._result

    def _build(self) -> Table:
        table = self._input_table
        names = table.column_names()
        out_names = self.output_schema.column_names()
        self.open()

        async def call(*values: Any) -> tuple:
            row = dict(zip(names, values))
            result = await self.invoke(**row)
            return tuple(result.get(n) for n in out_names)

        packed = expr.AsyncApplyExpression(
            call, tuple, False, False, tuple(table[n] for n in names), {}
        )
        with_packed = table.select(_pw_packed=packed)
        exprs = {n: with_packed._pw_packed[i] for i, n in enumerate(out_names)}
        result = with_packed.select(**exprs)
        result._schema = self.output_schema
        return result

    def with_options(self, **kwargs: Any) -> "AsyncTransformer":
        return self
