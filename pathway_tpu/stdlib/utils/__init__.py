"""Utility stdlib (parity: reference ``stdlib/utils``)."""

from pathway_tpu.stdlib.utils import bucketing, col, filtering
