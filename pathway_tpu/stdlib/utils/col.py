"""Column utilities (parity: reference ``stdlib/utils/col.py``)."""

from __future__ import annotations

from typing import Any, Callable, Sequence

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.table import Table


def _out_name(n: Any) -> str:
    return n.name if hasattr(n, "name") else str(n)


def unpack_col(column: expr.ColumnReference, *unpacked_columns: Any, schema: Any = None) -> Table:
    """Explode a tuple column into named columns."""
    table = column.table
    if schema is not None:
        names = schema.column_names()
    else:
        names = [_out_name(c) for c in unpacked_columns]
    exprs = {name: column[i] for i, name in enumerate(names)}
    return table.select(**exprs)


def multiapply_all_rows(
    *cols: expr.ColumnReference,
    fun: Callable[..., list[Sequence]],
    result_col_names: list[Any],
) -> Table:
    """Apply ``fun`` to entire columns at once (all rows together), producing several
    result columns keyed like the input table.

    Parity: reference ``stdlib/utils/col.py:211``. Mechanism: the whole table is folded
    into one row (a sorted tuple of ``(id, *values)`` rows), the function runs once per
    commit over the materialized columns, and the results are flattened back out and
    re-keyed by the original row ids. Meant for small tables / infrequent updates.
    """
    assert cols, "multiapply_all_rows needs at least one column"
    table = cols[0].table

    zipped = table.select(
        _pw_row=expr.apply(lambda *parts: tuple(parts), table.id, *cols)
    )
    reduced = zipped.reduce(_pw_rows=reducers.sorted_tuple(zipped._pw_row))

    names = [_out_name(n) for n in result_col_names]

    def fun_wrapped(rows: tuple) -> tuple:
        if not rows:
            return ()
        ids, *colvals = zip(*rows)
        results = [list(col) for col in fun(*[list(c) for c in colvals])]
        if len(results) != len(names):
            raise ValueError(
                f"multiapply_all_rows: fun returned {len(results)} columns, "
                f"expected {len(names)}"
            )
        for col in results:
            if len(col) != len(ids):
                raise ValueError(
                    f"multiapply_all_rows: fun returned a column of length {len(col)} "
                    f"for {len(ids)} input rows"
                )
        return tuple(zip(ids, *results))

    applied = reduced.select(_pw_out=expr.apply(fun_wrapped, reduced._pw_rows))
    flattened = applied.flatten(applied._pw_out)
    unpacked = unpack_col(flattened._pw_out, "_pw_id", *names)
    result = unpacked.with_id(unpacked._pw_id).without("_pw_id")
    result.promise_universe_is_equal_to(table)
    return result.with_universe_of(table)


def apply_all_rows(
    *cols: expr.ColumnReference,
    fun: Callable[..., Sequence],
    result_col_name: Any,
) -> Table:
    """Single-result-column variant of :func:`multiapply_all_rows`."""

    def fun_wrapped(*colvals: list) -> list[Sequence]:
        return [fun(*colvals)]

    return multiapply_all_rows(*cols, fun=fun_wrapped, result_col_names=[result_col_name])


def groupby_reduce_majority(column: expr.ColumnReference, value_column: expr.ColumnReference) -> Table:
    table = column.table

    value_column = table[value_column]
    counted = table.groupby(column, value_column).reduce(
        column, value_column, _pw_count=reducers.count()
    )
    from pathway_tpu.stdlib.utils.filtering import argmax_rows

    winners = argmax_rows(counted, counted[column.name], what=counted._pw_count)
    return winners.select(
        winners[column.name], majority=winners[value_column.name]
    )


def flatten_column(
    column: expr.ColumnReference,
    origin_id: "str | None" = "origin_id",
) -> Table:
    """Deprecated alias for ``Table.flatten`` (reference ``utils/col.py:16``)."""
    import warnings

    warnings.warn(
        "pw.stdlib.utils.col.flatten_column() is deprecated, use "
        "pw.Table.flatten() instead",
        DeprecationWarning,
        stacklevel=2,
    )
    return column.table.flatten(column, origin_id=origin_id)


def unpack_col_dict(column: expr.ColumnReference, schema: Any) -> Table:
    """Json-object column -> typed columns per ``schema`` (reference
    ``utils/col.py:143``); absent fields become None (optional dtypes)."""
    import pathway_tpu as pw
    from pathway_tpu.internals import dtype as dt
    from pathway_tpu.internals.json import Json

    table = column.table
    cols = {}
    for name, cs in schema.columns().items():
        target = cs.dtype

        def getter(cell: Any, _n: str = name, _t: Any = target) -> Any:
            obj = cell.value if isinstance(cell, Json) else cell
            v = (obj or {}).get(_n)
            if v is None:
                return None
            if _t.strip_optional() == dt.JSON:
                return Json(v)
            return v

        cols[name] = pw.apply_with_type(getter, target, column)
    return table.select(**cols)
