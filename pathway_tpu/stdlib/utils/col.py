"""Column utilities (parity: reference ``stdlib/utils/col.py``)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.table import Table


def unpack_col(column: expr.ColumnReference, *unpacked_columns: Any, schema: Any = None) -> Table:
    """Explode a tuple column into named columns."""
    table = column.table
    if schema is not None:
        names = schema.column_names()
    else:
        names = [c.name if hasattr(c, "name") else str(c) for c in unpacked_columns]
    exprs = {name: column[i] for i, name in enumerate(names)}
    return table.select(**exprs)


def multiapply_all_rows(*cols: expr.ColumnReference, fun: Any, result_col_names: list[str]) -> Table:
    """Apply a function over entire columns at once (all rows together)."""
    table = cols[0].table
    import pathway_tpu.internals.reducers as red

    grouped = table.groupby().reduce(
        _pw_keys=red.reducers.tuple(table.id),
        **{
            f"_pw_in_{i}": red.reducers.tuple(c)
            for i, c in enumerate(cols)
        },
    )

    def apply_fun(keys: tuple, *colvals: tuple) -> tuple:
        results = fun(*[list(c) for c in colvals])
        return tuple(zip(*results)) if len(result_col_names) > 1 else tuple(results)

    raise NotImplementedError(
        "multiapply_all_rows is not yet supported; use pw.apply on row level or a UDF"
    )


def apply_all_rows(*cols: expr.ColumnReference, fun: Any, result_col_name: str) -> Table:
    raise NotImplementedError(
        "apply_all_rows is not yet supported; use pw.apply on row level or a UDF"
    )


def groupby_reduce_majority(column: expr.ColumnReference, value_column: expr.ColumnReference) -> Table:
    table = column.table
    from pathway_tpu.internals.reducers import reducers

    counted = table.groupby(column, value_column).reduce(
        column, value_column, _pw_count=reducers.count()
    )
    return counted.groupby(counted[column.name]).reduce(
        counted[column.name],
        majority=reducers.argmax(counted._pw_count),
    )
