"""``pw.pandas_transformer`` (parity: reference ``stdlib/utils/pandas_transformer.py``)."""

from __future__ import annotations

import functools
from typing import Any, Callable

from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.table import Table


def pandas_transformer(
    output_schema: sch.SchemaMetaclass, output_universe: Any = None
) -> Callable:
    """Wrap a pandas-DataFrame function as a Table→Table transformer (batch semantics)."""

    def decorator(fun: Callable) -> Callable:
        @functools.wraps(fun)
        def wrapper(*tables: Table) -> Table:
            from pathway_tpu import debug

            raise NotImplementedError(
                "pandas_transformer requires full-table materialization mid-graph; "
                "apply the function to debug.table_to_pandas output, or use UDFs"
            )

        return wrapper

    return decorator
