"""``pw.pandas_transformer`` (parity: reference ``stdlib/utils/pandas_transformer.py``)."""

from __future__ import annotations

import functools
from typing import Any, Callable

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.keys import Pointer, pointer_from
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.utils.col import unpack_col


def _argument_index(fun: Callable, arg: Any) -> int | None:
    if arg is None or isinstance(arg, int):
        return arg
    import inspect

    names = list(inspect.signature(fun).parameters)
    try:
        return names.index(arg)
    except ValueError as exc:
        raise ValueError(f"wrong output universe. No argument of name: {arg}") from exc


def pandas_transformer(
    output_schema: sch.SchemaMetaclass, output_universe: Any = None
) -> Callable:
    """Wrap a pandas-DataFrame function as a Table→Table transformer.

    Each input table is materialized into a ``pd.DataFrame`` (index = row keys) once per
    commit; the function's resulting DataFrame is exploded back into an incremental table.
    Batch semantics — meant for small tables / infrequent updates, like the reference.
    """

    def decorator(fun: Callable) -> Callable:
        out_names = output_schema.column_names()
        universe_idx = _argument_index(fun, output_universe)

        @functools.wraps(fun)
        def wrapper(*tables: Table) -> Table:
            import pandas as pd

            if not tables:
                raise ValueError("pandas_transformer needs at least one input table")

            # Fold every input table into a single row keyed by the empty group key so
            # one apply sees all materialized inputs.
            reduced: list[Table] = []
            for table in tables:
                cols = [table[n] for n in table.column_names()]
                zipped = table.select(
                    _pw_row=expr.apply(lambda *parts: tuple(parts), table.id, *cols)
                )
                reduced.append(zipped.reduce(_pw_rows=reducers.sorted_tuple(zipped._pw_row)))

            first = reduced[0]
            col_names = [t.column_names() for t in tables]

            def run_pandas(*rowsets: tuple) -> tuple:
                frames = []
                for rows, names in zip(rowsets, col_names):
                    ids = [r[0] for r in rows]
                    data = {
                        name: [r[i + 1] for r in rows] for i, name in enumerate(names)
                    }
                    frames.append(pd.DataFrame(data, index=ids))
                result = fun(*frames)
                if isinstance(result, pd.Series):
                    result = pd.DataFrame(result)
                result.columns = out_names
                if universe_idx is not None and set(result.index) != set(
                    frames[universe_idx].index
                ):
                    # universe equality is a key-set property; row order may differ
                    raise ValueError(
                        "resulting universe does not match the universe of the indicated argument"
                    )
                if not result.index.is_unique:
                    raise ValueError("index of resulting DataFrame must be unique")
                out_rows = []
                for idx, row in zip(result.index, result.itertuples(index=False)):
                    key = idx if isinstance(idx, Pointer) else pointer_from(idx)
                    out_rows.append((key, *row))
                return tuple(out_rows)

            applied = first.select(
                _pw_out=expr.apply(run_pandas, *[t._pw_rows for t in reduced])
            )
            flattened = applied.flatten(applied._pw_out)
            unpacked = unpack_col(flattened._pw_out, "_pw_id", *out_names)
            output = unpacked.with_id(unpacked._pw_id).without("_pw_id")
            if universe_idx is not None:
                output.promise_universe_is_equal_to(tables[universe_idx])
                output = output.with_universe_of(tables[universe_idx])
            return output.update_types(**output_schema.typehints())

        return wrapper

    return decorator
