"""Row-filtering helpers (parity: reference ``stdlib/utils/filtering.py``)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.table import Table


def argmax_rows(table: Table, *on: expr.ColumnReference, what: Any) -> Table:
    """Keep, per group defined by ``on``, the single row maximizing ``what``."""
    reduced = table.groupby(*on).reduce(argmax_id=reducers.argmax(what))
    filter_table = reduced.with_id(reduced.argmax_id).promise_universe_is_subset_of(table)
    return table.restrict(filter_table)


def argmin_rows(table: Table, *on: expr.ColumnReference, what: Any) -> Table:
    """Keep, per group defined by ``on``, the single row minimizing ``what``."""
    reduced = table.groupby(*on).reduce(argmin_id=reducers.argmin(what))
    filter_table = reduced.with_id(reduced.argmin_id).promise_universe_is_subset_of(table)
    return table.restrict(filter_table)
