"""Louvain community detection.

Parity: reference ``stdlib/graphs/louvain_communities/impl.py`` — the parallel-move Louvain:
each round proposes, for every vertex, the adjacent cluster maximizing the modularity gain,
then executes an independent set of moves (no cluster participates in two moves, decided by
deterministic hash priorities) so rounds are order-independent and incremental.

Our formulation differs mechanically from the reference (total edge weight is attached via a
singleton aggregate joined by the empty group key rather than a gradual-broadcast operator;
priorities come from the engine's 128-bit fingerprints), but the objective math is the same.
"""

from __future__ import annotations

from typing import Any

import pathway_tpu.internals.expression as expr
from pathway_tpu.internals.keys import pointer_from
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.graphs.common import WeightedGraph
from pathway_tpu.stdlib.utils.filtering import argmax_rows


def _total_weight(edges: Table) -> Table:
    """Singleton table with the total edge weight ``m`` (keyed by the empty group key)."""
    return edges.groupby().reduce(m=reducers.sum(edges.weight))


def _propose_clusters(edges: Table, clustering: Table, total: Table) -> Table:
    """For each vertex, the adjacent cluster that locally maximizes the Louvain gain.

    ``edges``: directed (both directions present for undirected graphs), columns
    ``u``/``v``/``weight``. ``clustering``: keyed by vertex, column ``c``.
    Gain of moving v into cluster C' (unnormalized, reference impl.py:53):
    ``2*deg(v in C') - deg(v) * (2*deg(C') + deg(v)) / m``.
    """
    # sum of degrees per cluster (penalty term); zero placeholder so empty clusters exist
    placeholder_penalties = clustering.groupby(id=clustering.c).reduce(unscaled_penalty=0.0)
    by_u_cluster = edges.select(weight=edges.weight, cu=clustering.ix(edges.u).c)
    real_penalties = by_u_cluster.groupby(id=by_u_cluster.cu).reduce(
        unscaled_penalty=reducers.sum(by_u_cluster.weight)
    )
    cluster_penalties = placeholder_penalties.update_rows(real_penalties)

    # placeholder 0-degree rows keep isolated vertices representable (they still get
    # proposal rows via the placeholder vertex→own-cluster edges below)
    real_degrees = edges.groupby(id=edges.v).reduce(degree=reducers.sum(edges.weight))
    vertex_degrees = clustering.select(degree=0.0).update_rows(real_degrees)

    # self loops contribute to every candidate cluster equally; handled separately
    self_loops = edges.filter(edges.u == edges.v)
    loops_rekeyed = self_loops.with_id(self_loops.v)
    self_loop_by_v = loops_rekeyed.select(contr=loops_rekeyed.weight)
    self_loop_contribution = clustering.select(contr=0.0).update_rows(self_loop_by_v)

    proper = edges.filter(edges.u != edges.v)

    # vertex→cluster graph; zero-weight edges from each vertex to its own cluster keep
    # clusters with no incoming edges representable
    placeholder_edges = clustering.select(u=clustering.id, vc=clustering.c, weight=0.0)
    real_vc_edges = proper.select(
        u=proper.u, vc=clustering.ix(proper.v).c, weight=proper.weight
    )
    vertex_cluster_edges = placeholder_edges.concat_reindex(real_vc_edges)

    aggregated_gain = vertex_cluster_edges.groupby(
        vertex_cluster_edges.u, vertex_cluster_edges.vc
    ).reduce(
        vertex_cluster_edges.u,
        vertex_cluster_edges.vc,
        gain=reducers.sum(vertex_cluster_edges.weight),
    )
    # self-loop weight counts half (created doubled by contraction)
    aggregated_gain = aggregated_gain.select(
        aggregated_gain.u,
        aggregated_gain.vc,
        gain=aggregated_gain.gain
        + self_loop_contribution.ix(aggregated_gain.u).contr / 2.0,
    )

    def louvain_gain(gain: float, degree: float, penalty: float, total_w: float) -> float:
        return 2.0 * gain - degree * (2.0 * penalty + degree) / total_w

    gain_from_moving = aggregated_gain.select(
        aggregated_gain.u,
        aggregated_gain.vc,
        gain=expr.apply_with_type(
            louvain_gain,
            float,
            aggregated_gain.gain,
            vertex_degrees.ix(aggregated_gain.u).degree,
            cluster_penalties.ix(aggregated_gain.vc).unscaled_penalty,
            total.ix(aggregated_gain.pointer_from()).m,
        ),
    )

    # staying in the current cluster: remove own degree from the penalty
    stay_keyed = clustering.select(u=clustering.id, vc=clustering.c)
    gain_for_staying = stay_keyed.select(
        stay_keyed.u,
        stay_keyed.vc,
        gain=expr.apply_with_type(
            louvain_gain,
            float,
            # the aggregated gain for (u, own cluster) always exists via placeholder edges
            aggregated_gain.ix(
                stay_keyed.pointer_from(stay_keyed.u, stay_keyed.vc)
            ).gain,
            vertex_degrees.ix(stay_keyed.u).degree,
            cluster_penalties.ix(stay_keyed.vc).unscaled_penalty
            - vertex_degrees.ix(stay_keyed.u).degree,
            total.ix(stay_keyed.pointer_from()).m,
        ),
    )
    gain_for_staying = gain_for_staying.with_id_from(
        gain_for_staying.u, gain_for_staying.vc
    )

    moving_keyed = gain_from_moving.with_id_from(gain_from_moving.u, gain_from_moving.vc)
    ret = moving_keyed.update_rows(gain_for_staying)
    best = argmax_rows(ret, ret.u, what=ret.gain)
    rebased = best.with_id(best.u)
    proposal = rebased.select(c=rebased.vc)
    proposal.promise_universe_is_equal_to(clustering)
    return proposal.with_universe_of(clustering)


def _one_step(graph: WeightedGraph, clustering: Table, total: Table, iteration: int) -> Table:
    """One parallel Louvain round: propose moves, pick a cluster-disjoint subset, apply."""
    proposed = _propose_clusters(graph.WE, clustering, total)
    moves = proposed.filter(proposed.c != clustering.ix(proposed.id).c)
    candidate_moves = moves.select(
        u=moves.id,
        uc=clustering.ix(moves.id).c,
        vc=moves.c,
    )

    # deterministic per-(vertex, round) priority from the engine fingerprint
    def rand(p: Any, it: int = iteration) -> int:
        return int(pointer_from(p, it, "louvain").lo % (2**62))

    candidate_moves = candidate_moves.with_columns(
        r=expr.apply_with_type(rand, int, candidate_moves.u)
    )

    out_priorities = candidate_moves.select(candidate_moves.r, c=candidate_moves.uc)
    in_priorities = candidate_moves.select(candidate_moves.r, c=candidate_moves.vc)
    all_priorities = out_priorities.concat_reindex(in_priorities)
    maxima = argmax_rows(all_priorities, all_priorities.c, what=all_priorities.r)
    cluster_max_priority = maxima.with_id(maxima.c)

    winners = candidate_moves.filter(
        (candidate_moves.r == cluster_max_priority.ix(candidate_moves.uc).r)
        & (candidate_moves.r == cluster_max_priority.ix(candidate_moves.vc).r)
    )
    winners_rebased = winners.with_id(winners.u)
    delta = winners_rebased.select(c=winners_rebased.vc)
    updated = clustering.update_rows(delta)
    updated.promise_universe_is_equal_to(clustering)
    return updated.with_universe_of(clustering)


def louvain_level(graph: WeightedGraph, number_of_iterations: int = 10, *, total: Table | None = None) -> Table:
    """Run Louvain rounds on one level; returns a clustering keyed by vertex with ``c``.

    Parity: reference ``_louvain_level_fixed_iterations`` (impl.py:252). Fresh cluster ids
    are derived from vertex ids so every cluster id is one of its members.
    """
    if total is None:
        total = _total_weight(graph.WE)
    clustering = graph.V.select(c=graph.V.id)
    for iteration in range(number_of_iterations):
        clustering = _one_step(graph, clustering, total, iteration)
    return clustering


def louvain_communities(
    graph: WeightedGraph,
    levels: int = 1,
    iterations_per_level: int = 10,
) -> Table:
    """Hierarchical Louvain: run a level, contract clusters to vertices, repeat.

    Returns the flattened clustering of the *original* vertices after ``levels`` levels
    (column ``c``). Parity: reference ``louvain_communities_fixed_iterations``
    (impl.py:282) — we return the final level's flat clustering, the most commonly
    consumed artifact of the hierarchy.
    """
    total = _total_weight(graph.WE)
    # flat[v] = current cluster of original vertex v
    flat = graph.V.select(c=graph.V.id)
    level_graph = graph
    for _ in range(levels):
        clustering = louvain_level(level_graph, iterations_per_level, total=total)
        flat = flat.select(c=clustering.ix(flat.c).c)
        level_graph = level_graph.contracted_to_weighted_simple_graph(
            clustering, weight=reducers.sum(level_graph.WE.weight)
        )
    return flat


def exact_modularity(graph: WeightedGraph, clustering: Table, round_digits: int = 16) -> Table:
    """Modularity of ``clustering`` on ``graph`` (testing helper, reference impl.py:340)."""
    C = clustering
    WE = graph.WE
    clusters = C.groupby(id=C.c).reduce()

    by_cu = WE.select(WE.weight, cu=C.ix(WE.u).c)
    degrees = clusters.with_columns(degree=0.0).update_rows(
        by_cu.groupby(id=by_cu.cu).reduce(degree=reducers.sum(by_cu.weight))
    )
    both_ends = WE.select(WE.weight, cu=C.ix(WE.u).c, cv=C.ix(WE.v).c)
    internal_edges = both_ends.filter(both_ends.cu == both_ends.cv)
    internal = clusters.with_columns(internal=0.0).update_rows(
        internal_edges.groupby(id=internal_edges.cu).reduce(
            internal=reducers.sum(internal_edges.weight)
        )
    )
    total = _total_weight(WE)

    def cluster_modularity(internal_w: float, degree: float, total_w: float) -> float:
        return (internal_w * total_w - degree * degree) / (total_w * total_w)

    score = clusters.select(
        modularity=expr.apply_with_type(
            cluster_modularity,
            float,
            internal.ix(clusters.id).internal,
            degrees.ix(clusters.id).degree,
            total.ix(clusters.pointer_from()).m,
        )
    )
    summed = score.reduce(modularity=reducers.sum(score.modularity))
    return summed.select(
        modularity=expr.apply_with_type(
            lambda x, nd=round_digits: round(x, nd), float, summed.modularity
        )
    )
