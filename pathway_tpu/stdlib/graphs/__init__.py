"""Graph algorithms (parity: reference ``stdlib/graphs`` — pagerank, bellman_ford,
louvain_communities; all built from incremental Table ops)."""

from __future__ import annotations

from pathway_tpu.stdlib.graphs.common import Edge, Vertex, Weight, Clustering, Graph, WeightedGraph
from pathway_tpu.stdlib.graphs.pagerank import pagerank
from pathway_tpu.stdlib.graphs.bellman_ford import bellman_ford
from pathway_tpu.stdlib.graphs.louvain_communities import (
    exact_modularity,
    louvain_communities,
    louvain_level,
)

__all__ = [
    "Edge",
    "Vertex",
    "Weight",
    "Clustering",
    "Graph",
    "WeightedGraph",
    "pagerank",
    "bellman_ford",
    "louvain_communities",
    "louvain_level",
    "exact_modularity",
]
