"""Graph algorithms (parity: reference ``stdlib/graphs`` — pagerank, bellman_ford,
louvain_communities; all iterate-based)."""

from __future__ import annotations

from typing import Any

import pathway_tpu.internals.expression as expr
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.iterate import iterate
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.table import Table


class Edge:
    """Schema marker: edges have pointer columns u, v (reference ``graphs/common.py``)."""


class Vertex:
    pass


class Graph:
    def __init__(self, vertices: Table, edges: Table):
        self.V = vertices
        self.E = edges


def pagerank(edges: Table, steps: int = 5, damping: float = 0.85) -> Table:
    """Iterative pagerank over an edge table with ``u``/``v`` pointer columns.

    Returns a table keyed by vertex with a ``rank`` column (ints scaled by 1000, like the
    reference's fixed-point formulation).
    """
    degrees = edges.groupby(edges.u).reduce(degree=reducers.count())
    vertices_u = edges.select(v=edges.u)
    vertices_v = edges.select(v=edges.v)
    both = vertices_u.concat_reindex(vertices_v)
    vertices = both.groupby(both.v).reduce(v=both.v)

    def one_step(ranks: Table, edges: Table = edges, degrees: Table = degrees, vertices: Table = vertices) -> dict:
        deg = degrees
        # flow along edges: rank[u]/degree[u] summed into v
        edge_flow = edges.select(
            v=edges.v,
            flow=ranks.ix(edges.u).rank // deg.ix(edges.u, optional=False).degree,
        )
        inflow = edge_flow.groupby(edge_flow.v).reduce(
            v=edge_flow.v, total=reducers.sum(edge_flow.flow)
        )
        joined = vertices.join_left(inflow, vertices.v == inflow.v).select(
            v=vertices.v,
            rank=expr.coalesce(inflow.total, 0) * 5 // 6 + 1000 // 6,
        )
        new_ranks = joined.with_id(joined.v).select(rank=joined.rank)
        return dict(ranks=new_ranks)

    initial = vertices.with_id(vertices.v).select(rank=1000)
    result = iterate(one_step, iteration_limit=steps, ranks=initial)
    return result.ranks


def bellman_ford(vertices: Table, edges: Table) -> Table:
    """Single-source shortest paths: ``vertices`` needs ``is_source``; ``edges`` needs
    ``u``, ``v``, ``dist``."""
    import math

    initial = vertices.select(
        dist_from_source=expr.if_else(vertices.is_source, 0.0, math.inf)
    )

    def one_step(state: Table, edges: Table = edges) -> dict:
        relaxed = edges.select(
            v=edges.v,
            dist=state.ix(edges.u).dist_from_source + edges.dist,
        )
        best = relaxed.groupby(relaxed.v).reduce(
            v=relaxed.v, best=reducers.min(relaxed.dist)
        )
        best_by_vertex = best.with_id(best.v)
        new_state = state.select(
            dist_from_source=expr.coalesce(
                expr.apply_with_type(
                    lambda cur, new: min(cur, new) if new is not None else cur,
                    float,
                    state.dist_from_source,
                    best_by_vertex.ix(state.id, optional=True).best,
                ),
                state.dist_from_source,
            )
        )
        return dict(state=new_state)

    result = iterate(one_step, iteration_limit=50, state=initial)
    return result.state


def louvain_communities(graph: Any, **kwargs: Any) -> Table:
    raise NotImplementedError(
        "louvain_communities is planned for a later round (reference "
        "stdlib/graphs/louvain_communities/impl.py:385)"
    )
