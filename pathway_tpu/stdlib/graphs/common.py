"""Graph containers (parity: reference ``stdlib/graphs/{common,graph}.py``)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from pathway_tpu.internals.table import Table


class Vertex:
    """Schema marker (reference ``graphs/common.py``)."""


class Edge:
    """Edges have pointer columns ``u``, ``v``."""


class Weight:
    """Weighted edges additionally carry a float ``weight``."""


class Clustering:
    """A clustering assigns each vertex a cluster pointer ``c``."""


def _extended_to_full_clustering(vertices: Table, clustering: Table) -> Table:
    """Vertices missing from ``clustering`` become singleton clusters (their own id)."""
    return vertices.select(c=vertices.id).update_rows(clustering)


@dataclass
class Graph:
    """Undirected unweighted (multi)graph: vertex table + ``u``/``v`` edge table."""

    V: Table
    E: Table

    def contracted_to_multi_graph(self, clustering: Table) -> "Graph":
        full = _extended_to_full_clustering(self.V, clustering)
        return Graph(_contract_vertices(full), _contract_edges(self.E, full, keep=[]))

    def without_self_loops(self) -> "Graph":
        return Graph(self.V, self.E.filter(self.E.u != self.E.v))


def _contract_vertices(full_clustering: Table) -> Table:
    grouped = full_clustering.groupby(full_clustering.c).reduce(v=full_clustering.c)
    return grouped.with_id(grouped.v)


def _contract_edges(edges: Table, full_clustering: Table, *, keep: list[str]) -> Table:
    exprs = {
        "u": full_clustering.ix(edges.u).c,
        "v": full_clustering.ix(edges.v).c,
    }
    for name in keep:
        exprs[name] = edges[name]
    return edges.select(**exprs)


@dataclass
class WeightedGraph(Graph):
    """Graph whose edges carry weights; ``WE`` has columns ``u``, ``v``, ``weight``."""

    WE: Table = None  # type: ignore[assignment]

    @staticmethod
    def from_vertices_and_weighted_edges(V: Table, WE: Table) -> "WeightedGraph":
        return WeightedGraph(V, WE, WE)

    def contracted_to_multi_graph(self, clustering: Table) -> "WeightedGraph":
        full = _extended_to_full_clustering(self.V, clustering)
        contracted = _contract_edges(self.WE, full, keep=["weight"])
        return WeightedGraph.from_vertices_and_weighted_edges(
            _contract_vertices(full), contracted
        )

    def contracted_to_weighted_simple_graph(self, clustering: Table, **reducer_expressions: Any) -> "WeightedGraph":
        contracted = self.contracted_to_multi_graph(clustering)
        we = contracted.WE
        simple = we.groupby(we.u, we.v).reduce(we.u, we.v, **reducer_expressions)
        return WeightedGraph.from_vertices_and_weighted_edges(contracted.V, simple)

    def without_self_loops(self) -> "WeightedGraph":
        return WeightedGraph.from_vertices_and_weighted_edges(
            self.V, self.WE.filter(self.WE.u != self.WE.v)
        )
