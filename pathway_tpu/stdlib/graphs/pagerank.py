"""Pagerank (parity: reference ``stdlib/graphs/pagerank/impl.py``)."""

from __future__ import annotations

import pathway_tpu.internals.expression as expr
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.table import Table


def pagerank(edges: Table, steps: int = 5) -> Table:
    """Pagerank over an edge table with ``u``/``v`` pointer columns.

    Returns a table keyed by vertex with an int ``rank`` column (fixed-point scaled,
    damping 5/6, matching the reference's integer formulation).
    """
    in_vertices = edges.groupby(id=edges.v).reduce(degree=0)
    out_vertices = edges.groupby(id=edges.u).reduce(degree=reducers.count())
    degrees = in_vertices.update_rows(out_vertices)
    # vertices with outgoing edges only never receive flow: constant base rank
    base = out_vertices.difference(in_vertices).select(rank=1_000)

    ranks = degrees.select(rank=6_000)

    for _step in range(steps):
        outflow = degrees.select(
            flow=expr.if_else(
                degrees.degree == 0, 0, (ranks.rank * 5) // (degrees.degree * 6)
            ),
        )
        # flow is INLINED onto the edges via an explicit join (not an ix cross
        # reference): joins and groupbys exchange rows by key, so this runs
        # unchanged under spawn -n N, where a reducer-side cross-table read
        # could not be resolved remotely
        contrib = edges.join(outflow, edges.u == outflow.id).select(
            v=edges.v, flow=outflow.flow
        )
        inflows = contrib.groupby(id=contrib.v).reduce(
            rank=reducers.sum(contrib.flow) + 1_000
        )
        combined = base.concat(inflows)
        combined.promise_universe_is_equal_to(degrees)
        ranks = combined.with_universe_of(degrees)

    return ranks
