"""Bellman-Ford shortest paths (parity: reference ``stdlib/graphs/bellman_ford.py``)."""

from __future__ import annotations

import math

import pathway_tpu.internals.expression as expr
from pathway_tpu.internals.iterate import iterate
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.table import Table


def bellman_ford(vertices: Table, edges: Table) -> Table:
    """Single-source shortest paths: ``vertices`` needs ``is_source``; ``edges`` needs
    ``u``, ``v``, ``dist``."""
    initial = vertices.select(
        dist_from_source=expr.if_else(vertices.is_source, 0.0, math.inf)
    )

    def one_step(state: Table, edges: Table = edges) -> dict:
        relaxed = edges.select(
            v=edges.v,
            dist=state.ix(edges.u).dist_from_source + edges.dist,
        )
        best = relaxed.groupby(relaxed.v).reduce(
            v=relaxed.v, best=reducers.min(relaxed.dist)
        )
        best_by_vertex = best.with_id(best.v)
        new_state = state.select(
            dist_from_source=expr.coalesce(
                expr.apply_with_type(
                    lambda cur, new: min(cur, new) if new is not None else cur,
                    float,
                    state.dist_from_source,
                    best_by_vertex.ix(state.id, optional=True).best,
                ),
                state.dist_from_source,
            )
        )
        return dict(state=new_state)

    result = iterate(one_step, iteration_limit=50, state=initial)
    return result.state
