"""Live visualization (parity: reference ``stdlib/viz`` — Bokeh/Panel auto-updating
plots and table widgets). Bokeh/Panel are optional; without them ``plot``/``show``
degrade with a clear error while ``table_snapshot`` (plain data) always works."""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict

from pathway_tpu.internals.table import Table


def _require_bokeh() -> None:
    try:
        import bokeh  # noqa: F401
        import panel  # noqa: F401
    except ImportError as exc:
        raise ImportError(
            "bokeh/panel are not available in this environment; use "
            "pw.viz.table_snapshot(table) for the raw updating data"
        ) from exc


class _SnapshotCollector:
    """Subscribes to a table, maintains the current snapshot thread-safely."""

    def __init__(self, table: Table):
        self.rows: Dict[Any, dict] = {}
        self.lock = threading.Lock()
        self.listeners: list[Callable[[list], None]] = []
        from pathway_tpu.io import subscribe

        def on_change(key: Any, row: dict, time: int, is_addition: bool) -> None:
            with self.lock:
                if is_addition:
                    self.rows[key] = row
                else:
                    self.rows.pop(key, None)
                current = [dict(r) for r in self.rows.values()]
            for listener in self.listeners:
                listener(current)

        subscribe(table, on_change)

    def snapshot(self) -> list[dict]:
        with self.lock:
            return [dict(r) for r in self.rows.values()]


def table_snapshot(table: Table) -> _SnapshotCollector:
    """A live snapshot collector over ``table`` (works without bokeh/panel)."""
    return _SnapshotCollector(table)


def plot(table: Table, plotting_function: Callable, sorting_col: Any = None) -> Any:
    """Bokeh plot auto-updating as the table changes (reference ``viz/plotting.py:35``)."""
    _require_bokeh()
    from bokeh.models import ColumnDataSource
    import pandas as pd
    import panel as pn

    collector = _SnapshotCollector(table)
    frame = pd.DataFrame(collector.snapshot())
    source = ColumnDataSource(frame)
    figure = plotting_function(source)

    def refresh(current: list) -> None:
        df = pd.DataFrame(current)
        if sorting_col is not None and sorting_col in df:
            df = df.sort_values(sorting_col)
        source.data = dict(ColumnDataSource(df).data)

    collector.listeners.append(refresh)
    return pn.Column(figure)


def show(table: Table, **kwargs: Any) -> Any:
    """Live table widget (reference ``viz`` ``Table.show``)."""
    _require_bokeh()
    import pandas as pd
    import panel as pn

    collector = _SnapshotCollector(table)
    widget = pn.widgets.Tabulator(pd.DataFrame(collector.snapshot()), **kwargs)

    def refresh(current: list) -> None:
        widget.value = pd.DataFrame(current)

    collector.listeners.append(refresh)
    return widget
