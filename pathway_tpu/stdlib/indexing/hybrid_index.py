"""Hybrid retrieval with reciprocal-rank fusion.

Parity: reference ``stdlib/indexing/hybrid_index.py:14`` (``HybridIndex`` — RRF over any
number of inner indexes, typically BM25 + KNN).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.data_index import DataIndex, InnerIndex
from pathway_tpu.stdlib.indexing.retrievers import AbstractRetrieverFactory


class _HybridInstance:
    def __init__(self, instances: List[Any], k: float):
        self.instances = instances
        self.k = k

    def add(self, key: Any, value: Any, filter_data: Any = None) -> None:
        # value is a tuple: one entry per inner index (e.g. (vector, text))
        values = value if isinstance(value, tuple) and len(value) == len(self.instances) else (
            (value,) * len(self.instances)
        )
        for inst, v in zip(self.instances, values):
            inst.add(key, v, filter_data)

    def remove(self, key: Any) -> None:
        for inst in self.instances:
            inst.remove(key)

    def search(self, query: Any, limit: int, filter_expr: Any = None) -> List[tuple]:
        queries = query if isinstance(query, tuple) and len(query) == len(self.instances) else (
            (query,) * len(self.instances)
        )
        fused: Dict[Any, float] = {}
        for inst, q in zip(self.instances, queries):
            results = inst.search(q, max(limit * 2, 10), filter_expr)
            for rank, (key, _score) in enumerate(results):
                fused[key] = fused.get(key, 0.0) + 1.0 / (self.k + rank + 1)
        ranked = sorted(fused.items(), key=lambda kv: -kv[1])[:limit]
        return [(key, score) for key, score in ranked]


class HybridIndex(InnerIndex):
    def __init__(self, inner_indexes: List[InnerIndex], *, k: float = 60.0):
        first = inner_indexes[0]
        super().__init__(first.data_column, first.metadata_column)
        self.inner_indexes = inner_indexes
        self.k = k

    def make_instance_factory(self) -> Any:
        factories = [ix.make_instance_factory() for ix in self.inner_indexes]
        k = self.k
        return lambda: _HybridInstance([f() for f in factories], k)

    def preprocess_query(self, query_column: expr.ColumnReference) -> expr.ColumnExpression:
        processed = [ix.preprocess_query(query_column) for ix in self.inner_indexes]
        return expr.make_tuple(*processed)


@dataclass
class HybridIndexFactory(AbstractRetrieverFactory):
    retriever_factories: List[AbstractRetrieverFactory] = field(default_factory=list)
    k: float = 60.0

    def build_index(
        self,
        data_column: expr.ColumnReference,
        data_table: Table,
        metadata_column: expr.ColumnReference | None = None,
        **kwargs: Any,
    ) -> DataIndex:
        inner = [
            f.build_inner_index(data_column, metadata_column)
            for f in self.retriever_factories
        ]
        hybrid = HybridIndex(inner, k=self.k)
        # the hybrid instance receives one value per sub-index; data column stays shared
        return _HybridDataIndex(data_table, hybrid)


class _HybridDataIndex(DataIndex):
    pass
