"""Metadata filtering for index queries.

Parity: reference ``DerivedFilteredSearchIndex`` (``src/external_integration/mod.rs:373``) which
uses jmespath. We support the jmespath subset the xpack templates actually use —
``field == 'value'``, ``contains(field, 'x')``, ``globmatch('pat', path)``, boolean
&&/||/!, parenthesization — over Json metadata.
"""

from __future__ import annotations

import fnmatch
import re
from typing import Any

from pathway_tpu.internals.json import Json


def _resolve(data: Any, path: str) -> Any:
    if isinstance(data, Json):
        data = data.value
    if data is None:
        return None
    cur = data
    for part in path.split("."):
        part = part.strip().strip("`")
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            return None
    return cur


_TOKENIZER = re.compile(
    r"\s*(?:(?P<lp>\()|(?P<rp>\))|(?P<comma>,)|(?P<and>&&)|(?P<or>\|\|)|(?P<not>!)"
    r"|(?P<op>==|!=|>=|<=|>|<)|(?P<str>'(?:\\'|[^'])*'|`[^`]*`)|(?P<num>-?\d+(?:\.\d+)?)"
    r"|(?P<fn>[a-zA-Z_][\w]*\s*\()|(?P<id>[a-zA-Z_][\w.]*))"
)


class _FilterParser:
    def __init__(self, text: str):
        self.tokens: list[tuple[str, str]] = []
        pos = 0
        while pos < len(text):
            m = _TOKENIZER.match(text, pos)
            if m is None:
                if text[pos:].strip() == "":
                    break
                raise ValueError(f"bad filter near {text[pos:]!r}")
            kind = m.lastgroup
            self.tokens.append((kind, m.group().strip()))
            pos = m.end()
        self.pos = 0

    def peek(self) -> tuple[str, str] | None:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self) -> tuple[str, str]:
        tok = self.tokens[self.pos]
        self.pos += 1
        return tok

    def parse(self) -> Any:
        return self.parse_or()

    def parse_or(self) -> Any:
        left = self.parse_and()
        while self.peek() and self.peek()[0] == "or":
            self.next()
            right = self.parse_and()
            left = ("or", left, right)
        return left

    def parse_and(self) -> Any:
        left = self.parse_not()
        while self.peek() and self.peek()[0] == "and":
            self.next()
            right = self.parse_not()
            left = ("and", left, right)
        return left

    def parse_not(self) -> Any:
        if self.peek() and self.peek()[0] == "not":
            self.next()
            return ("not", self.parse_not())
        return self.parse_cmp()

    def parse_cmp(self) -> Any:
        left = self.parse_atom()
        if self.peek() and self.peek()[0] == "op":
            op = self.next()[1]
            right = self.parse_atom()
            return ("cmp", op, left, right)
        return left

    def parse_atom(self) -> Any:
        kind, text = self.next()
        if kind == "lp":
            inner = self.parse()
            self.next()  # rp
            return inner
        if kind == "str":
            return ("lit", text[1:-1].replace("\\'", "'"))
        if kind == "num":
            return ("lit", float(text) if "." in text else int(text))
        if kind == "fn":
            name = text[:-1].strip()
            args = []
            while True:
                nxt = self.peek()
                if nxt is None or nxt[0] == "rp":
                    if nxt:
                        self.next()
                    break
                args.append(self.parse())
                if self.peek() and self.peek()[0] == "comma":
                    self.next()
            return ("fn", name, args)
        if kind == "id":
            return ("path", text)
        raise ValueError(f"unexpected token {text!r}")


def _eval(node: Any, data: Any) -> Any:
    kind = node[0]
    if kind == "lit":
        return node[1]
    if kind == "path":
        return _resolve(data, node[1])
    if kind == "cmp":
        _, op, l, r = node
        lv, rv = _eval(l, data), _eval(r, data)
        try:
            return {
                "==": lv == rv,
                "!=": lv != rv,
                ">": lv > rv,
                ">=": lv >= rv,
                "<": lv < rv,
                "<=": lv <= rv,
            }[op]
        except TypeError:
            return False
    if kind == "and":
        return bool(_eval(node[1], data)) and bool(_eval(node[2], data))
    if kind == "or":
        return bool(_eval(node[1], data)) or bool(_eval(node[2], data))
    if kind == "not":
        return not bool(_eval(node[1], data))
    if kind == "fn":
        _, name, args = node
        vals = [_eval(a, data) for a in args]
        if name == "contains":
            hay, needle = vals[0], vals[1]
            try:
                return needle in hay
            except TypeError:
                return False
        if name == "globmatch":
            pattern, value = vals[0], vals[1]
            return fnmatch.fnmatch(str(value or ""), str(pattern))
        if name == "starts_with":
            return str(vals[1] or "").startswith(str(vals[0]))
        raise ValueError(f"unsupported filter function {name!r}")
    raise ValueError(f"bad filter node {node!r}")


def matches_filter(metadata: Any, filter_expr: Any) -> bool:
    """True when metadata passes the filter; filters on absent metadata fail closed."""
    if filter_expr is None:
        return True
    if callable(filter_expr):
        return bool(filter_expr(metadata))
    tree = _FilterParser(str(filter_expr)).parse()
    return bool(_eval(tree, metadata))
