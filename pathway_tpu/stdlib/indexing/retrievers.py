"""Retriever factory protocol (parity: reference ``stdlib/indexing/retrievers.py``)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any


class AbstractRetrieverFactory(ABC):
    """Builds a DataIndex over a data table + column (used by DocumentStore)."""

    @abstractmethod
    def build_index(self, data_column: Any, data_table: Any, **kwargs: Any) -> Any:
        ...
