"""Vector document index presets (parity: reference ``vector_document_index.py``)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.data_index import DataIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnn,
    BruteForceKnnMetricKind,
)


def default_vector_document_index(
    data_column: expr.ColumnReference,
    data_table: Table,
    *,
    embedder: Any = None,
    dimensions: int | None = None,
    metadata_column: expr.ColumnReference | None = None,
) -> DataIndex:
    if dimensions is None:
        from pathway_tpu.stdlib.indexing.nearest_neighbors import _probe_embedder_dims

        dimensions = _probe_embedder_dims(embedder)
    return DataIndex(
        data_table,
        BruteForceKnn(
            data_column,
            metadata_column,
            dimensions=dimensions,
            metric=BruteForceKnnMetricKind.COS,
            embedder=embedder,
        ),
    )
