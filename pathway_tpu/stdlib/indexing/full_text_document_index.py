"""Full-text document index preset (parity: reference ``full_text_document_index.py``)."""

from __future__ import annotations

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.bm25 import TantivyBM25
from pathway_tpu.stdlib.indexing.data_index import DataIndex


def default_full_text_document_index(
    data_column: expr.ColumnReference,
    data_table: Table,
    *,
    metadata_column: expr.ColumnReference | None = None,
) -> DataIndex:
    return DataIndex(data_table, TantivyBM25(data_column, metadata_column))
