"""DataIndex / InnerIndex — typed index querying over tables.

Parity: reference ``stdlib/indexing/data_index.py`` (``DataIndex:278``, ``InnerIndex:206``).
The query path compiles to the engine's as-of-now external-index operator
(``pathway_tpu/engine/evaluators.py::ExternalIndexEvaluator`` ↔ reference
``external_index.rs:38``); KNN search itself runs as a jit'd matmul+top_k on the TPU.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.table import Table


class InnerIndex:
    """Engine-facing index description: data column + factory for per-worker instances."""

    def __init__(
        self,
        data_column: expr.ColumnReference,
        metadata_column: expr.ColumnReference | None = None,
    ):
        self.data_column = data_column
        self.metadata_column = metadata_column

    def make_instance_factory(self) -> Any:
        raise NotImplementedError

    def preprocess_query(self, query_column: expr.ColumnReference) -> expr.ColumnExpression:
        """Hook: e.g. embed text queries before the index sees them."""
        return query_column

    def preprocess_data(self, data_column: expr.ColumnReference) -> expr.ColumnExpression:
        """Hook: e.g. embed indexed documents (text column → vector column)."""
        return data_column


class _InstanceFactory:
    def __init__(self, make: Callable[[], Any]):
        self._make = make

    def make_instance(self) -> Any:
        return self._make()


class DataIndex:
    """Index over ``data_table``; querying returns per-query matched rows.

    ``query_as_of_now`` gives as-of-now semantics (answers never retracted on index change;
    used by RAG serving); ``query`` re-answers queries when the index updates.
    """

    def __init__(
        self,
        data_table: Table,
        inner_index: InnerIndex,
    ):
        self.data_table = data_table
        self.inner_index = inner_index
        # build the (possibly embedded) index-side table ONCE: every query surface shares
        # it, so the corpus crosses the TPU embedder a single time per document update
        self._index_table = data_table.select(
            _pw_vec=inner_index.preprocess_data(inner_index.data_column),
            **(
                {"_pw_meta": inner_index.metadata_column}
                if inner_index.metadata_column is not None
                else {}
            ),
        )

    def query_as_of_now(
        self,
        query_column: expr.ColumnReference,
        *,
        number_of_matches: Any = 3,
        collapse_rows: bool = True,
        metadata_filter: expr.ColumnExpression | None = None,
    ) -> Table:
        return self._query(
            query_column,
            number_of_matches=number_of_matches,
            collapse_rows=collapse_rows,
            metadata_filter=metadata_filter,
            as_of_now=True,
        )

    def query(
        self,
        query_column: expr.ColumnReference,
        *,
        number_of_matches: Any = 3,
        collapse_rows: bool = True,
        metadata_filter: expr.ColumnExpression | None = None,
    ) -> Table:
        return self._query(
            query_column,
            number_of_matches=number_of_matches,
            collapse_rows=collapse_rows,
            metadata_filter=metadata_filter,
            as_of_now=False,
        )

    def _query(
        self,
        query_column: expr.ColumnReference,
        *,
        number_of_matches: Any,
        collapse_rows: bool,
        metadata_filter: expr.ColumnExpression | None,
        as_of_now: bool,
    ) -> Table:
        queries = query_column.table
        processed_query = self.inner_index.preprocess_query(query_column)
        query_table = queries.select(
            _pw_query=processed_query,
            _pw_limit=number_of_matches,
            **(
                {"_pw_qfilter": metadata_filter}
                if metadata_filter is not None
                else {}
            ),
        )
        index_table = self._index_table
        reply = query_table._external_index_as_of_now(
            index_table,
            index_column=index_table._pw_vec,
            query_column=query_table._pw_query,
            index_factory=_InstanceFactory(self.inner_index.make_instance_factory()),
            res_type=dt.ANY,
            query_responses_limit_column=query_table._pw_limit,
            index_filter_data_column=(
                index_table._pw_meta if self.inner_index.metadata_column is not None else None
            ),
            query_filter_column=(
                query_table._pw_qfilter if metadata_filter is not None else None
            ),
            asof_now=as_of_now,
        )
        # reply: per query key, tuple of (data_key, score)
        if not collapse_rows:
            flat = reply.flatten(reply._pw_index_reply, origin_id="_pw_query_id")
            matched = flat.select(
                _pw_query_id=flat._pw_query_id,
                _pw_match_ptr=flat._pw_index_reply[0],
                _pw_index_reply_score=flat._pw_index_reply[1],
            )
            data_cols = {
                name: self.data_table.ix(matched._pw_match_ptr)[name]
                for name in self.data_table.column_names()
            }
            return matched.select(
                matched._pw_query_id, matched._pw_index_reply_score, **data_cols
            )

        flat = reply.flatten(reply._pw_index_reply, origin_id="_pw_query_id")
        matched = flat.select(
            _pw_query_id=flat._pw_query_id,
            _pw_match_ptr=flat._pw_index_reply[0],
            _pw_score=flat._pw_index_reply[1],
        )
        data_rows = self.data_table.ix(matched._pw_match_ptr)
        enriched_cols = {
            name: data_rows[name] for name in self.data_table.column_names()
        }
        enriched = matched.select(
            matched._pw_query_id, matched._pw_score, **enriched_cols
        )
        grouped = enriched.groupby(enriched._pw_query_id).reduce(
            enriched._pw_query_id,
            _pw_index_reply_score=reducers.tuple(
                enriched._pw_score, sort_by=-enriched._pw_score
            ),
            **{
                name: reducers.tuple(enriched[name], sort_by=-enriched._pw_score)
                for name in self.data_table.column_names()
            },
        )
        rekeyed = grouped.with_id(grouped._pw_query_id).without("_pw_query_id")
        # left-join back (keyed by the query id) so zero-match queries still produce a row
        joined = queries.join_left(rekeyed, queries.id == rekeyed.id, id=queries.id).select(
            *[queries[n] for n in queries.column_names()],
            **{
                "_pw_index_reply_score": expr.coalesce(
                    rekeyed._pw_index_reply_score, expr.make_tuple()
                ),
            },
            **{
                name: expr.coalesce(rekeyed[name], expr.make_tuple())
                for name in self.data_table.column_names()
            },
        )
        return joined
