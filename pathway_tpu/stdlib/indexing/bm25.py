"""Full-text BM25 index.

Parity: reference ``stdlib/indexing/bm25.py`` (``TantivyBM25:41`` over
``tantivy_integration.rs``). Tantivy is a Rust library; here BM25 is a host-side inverted
index (text scoring is memory-bound pointer chasing — CPU-appropriate; dense retrieval is
what belongs on the TPU).
"""

from __future__ import annotations

import math
import re
from collections import Counter, defaultdict
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.data_index import DataIndex, InnerIndex
from pathway_tpu.stdlib.indexing.filters import matches_filter
from pathway_tpu.stdlib.indexing.retrievers import AbstractRetrieverFactory

_TOKEN_RE = re.compile(r"[A-Za-z0-9_]+")


def _tokenize(text: str) -> List[str]:
    return [t.lower() for t in _TOKEN_RE.findall(text or "")]


class BM25Index:
    """Incremental BM25 inverted index with removals (k1/b per the standard formula)."""

    def __init__(self, k1: float = 1.2, b: float = 0.75):
        self.k1 = k1
        self.b = b
        self.postings: Dict[str, Dict[Any, int]] = defaultdict(dict)
        self.doc_len: Dict[Any, int] = {}
        self.doc_tokens: Dict[Any, Counter] = {}
        self.filter_data: Dict[Any, Any] = {}
        self.total_len = 0

    def add(self, key: Any, text: Any, filter_data: Any = None) -> None:
        if key in self.doc_len:
            self.remove(key)
        tokens = Counter(_tokenize(str(text)))
        self.doc_tokens[key] = tokens
        n = sum(tokens.values())
        self.doc_len[key] = n
        self.total_len += n
        for term, count in tokens.items():
            self.postings[term][key] = count
        if filter_data is not None:
            self.filter_data[key] = filter_data

    def remove(self, key: Any) -> None:
        tokens = self.doc_tokens.pop(key, None)
        if tokens is None:
            return
        self.total_len -= self.doc_len.pop(key)
        for term in tokens:
            self.postings[term].pop(key, None)
            if not self.postings[term]:
                del self.postings[term]
        self.filter_data.pop(key, None)

    def search(self, query: Any, limit: int, filter_expr: Any = None) -> List[tuple]:
        n_docs = len(self.doc_len)
        if n_docs == 0:
            return []
        avg_len = self.total_len / n_docs
        scores: Dict[Any, float] = defaultdict(float)
        for term in _tokenize(str(query)):
            posting = self.postings.get(term)
            if not posting:
                continue
            idf = math.log(1 + (n_docs - len(posting) + 0.5) / (len(posting) + 0.5))
            for key, tf in posting.items():
                denom = tf + self.k1 * (1 - self.b + self.b * self.doc_len[key] / avg_len)
                scores[key] += idf * tf * (self.k1 + 1) / denom
        ranked = sorted(scores.items(), key=lambda kv: -kv[1])
        out = []
        for key, score in ranked:
            if filter_expr is not None and not matches_filter(
                self.filter_data.get(key), filter_expr
            ):
                continue
            out.append((key, float(score)))
            if len(out) >= limit:
                break
        return out


class TantivyBM25(InnerIndex):
    """BM25 inner index (name kept for API parity with the reference)."""

    def __init__(
        self,
        data_column: expr.ColumnReference,
        metadata_column: expr.ColumnReference | None = None,
        *,
        ram_budget: int = 50_000_000,
        in_memory_index: bool = True,
    ):
        super().__init__(data_column, metadata_column)

    def make_instance_factory(self) -> Any:
        return lambda: BM25Index()


@dataclass
class TantivyBM25Factory(AbstractRetrieverFactory):
    ram_budget: int = 50_000_000
    in_memory_index: bool = True

    def build_inner_index(
        self,
        data_column: expr.ColumnReference,
        metadata_column: expr.ColumnReference | None = None,
    ) -> InnerIndex:
        return TantivyBM25(
            data_column,
            metadata_column,
            ram_budget=self.ram_budget,
            in_memory_index=self.in_memory_index,
        )

    def build_index(
        self,
        data_column: expr.ColumnReference,
        data_table: Table,
        metadata_column: expr.ColumnReference | None = None,
        **kwargs: Any,
    ) -> DataIndex:
        return DataIndex(data_table, self.build_inner_index(data_column, metadata_column))
