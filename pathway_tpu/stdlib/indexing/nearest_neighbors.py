"""KNN inner indexes & factories.

Parity: reference ``stdlib/indexing/nearest_neighbors.py`` (``USearchKnn:65``,
``BruteForceKnn:170``, ``LshKnn:262``, factories ``:407-528``). TPU-native mechanism: exact
brute force is a jit'd MXU matmul + ``lax.top_k`` (``pathway_tpu/ops/knn.py``); USearchKnn
(HNSW ANN in the reference) is served by the same exact kernel — on TPU, exact search over
10M×384 vectors is a single fused matmul well inside the latency budget, so approximate
graph-walk indexes are unnecessary until far larger corpora.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, Callable, Optional

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.table import Table
from pathway_tpu.ops.knn import BruteForceKnnIndex, LshKnnIndex
from pathway_tpu.stdlib.indexing.data_index import DataIndex, InnerIndex
from pathway_tpu.stdlib.indexing.retrievers import AbstractRetrieverFactory


class BruteForceKnnMetricKind(enum.Enum):
    L2SQ = "l2sq"
    COS = "cos"
    IP = "ip"


class USearchMetricKind(enum.Enum):
    L2SQ = "l2sq"
    COS = "cos"
    IP = "ip"


def _metric_str(metric: Any) -> str:
    if isinstance(metric, enum.Enum):
        return str(metric.value)
    return str(metric)


class _KnnInnerIndex(InnerIndex):
    def __init__(
        self,
        data_column: expr.ColumnReference,
        metadata_column: expr.ColumnReference | None,
        dimensions: int,
        metric: Any,
        embedder: Any = None,
        make_index: Callable[[], Any] | None = None,
    ):
        super().__init__(data_column, metadata_column)
        self.dimensions = dimensions
        self.metric = _metric_str(metric)
        self.embedder = embedder
        self._make_index = make_index

    def make_instance_factory(self) -> Callable[[], Any]:
        return self._make_index

    # Indexes whose search kernel consumes device-resident query vectors override
    # this to True: query embeddings then stay on device and chain into the search
    # with one total round-trip. Host-side indexes (LSH) keep numpy cells.
    _device_queries = False

    def preprocess_query(self, query_column: expr.ColumnReference) -> expr.ColumnExpression:
        if self.embedder is not None:
            device = getattr(self.embedder, "device_expression", None)
            if self._device_queries and device is not None:
                return device(query_column)
            return _apply_embedder(self.embedder, query_column)
        return query_column

    def preprocess_data(self, data_column: expr.ColumnReference) -> expr.ColumnExpression:
        if self.embedder is not None:
            return _apply_embedder(self.embedder, data_column)
        return data_column


def _apply_embedder(embedder: Any, column: Any) -> expr.ColumnExpression:
    from pathway_tpu.internals.udfs import UDF

    if isinstance(embedder, UDF) or callable(embedder):
        result = embedder(column)
        if isinstance(result, expr.ColumnExpression):
            return result
    raise TypeError("embedder must be a pw.UDF or callable producing an expression")


def _make_bf_index(dimensions: int, metric_s: str, reserved_space: int) -> Any:
    """Engine-facing index instance; a configured multi-shard mesh swaps in the
    row-sharded store with all-gather top-k merge (the reference's per-worker sharded
    index, ``external_index.rs`` + ``shard.rs``)."""
    from pathway_tpu.parallel.mesh import data_shards, get_default_mesh

    mesh = get_default_mesh()
    return BruteForceKnnIndex(
        dimensions,
        metric=metric_s,
        initial_capacity=max(16, reserved_space),
        mesh=mesh if data_shards(mesh) > 1 else None,
    )


class BruteForceKnn(_KnnInnerIndex):
    """Exact KNN on the TPU (reference ``BruteForceKnn:170`` over
    ``brute_force_knn_integration.rs``)."""

    _device_queries = True  # dense store consumes device query batches directly

    def __init__(
        self,
        data_column: expr.ColumnReference,
        metadata_column: expr.ColumnReference | None = None,
        *,
        dimensions: int,
        reserved_space: int = 1024,
        auxiliary_space: int = 1024,
        metric: BruteForceKnnMetricKind = BruteForceKnnMetricKind.L2SQ,
        embedder: Any = None,
    ):
        metric_s = _metric_str(metric)
        super().__init__(
            data_column,
            metadata_column,
            dimensions,
            metric_s,
            embedder,
            make_index=lambda: _make_bf_index(dimensions, metric_s, reserved_space),
        )


class USearchKnn(_KnnInnerIndex):
    """API parity with the reference's HNSW index; served exactly on TPU (see module doc)."""

    _device_queries = True  # same dense-store kernel as BruteForceKnn

    def __init__(
        self,
        data_column: expr.ColumnReference,
        metadata_column: expr.ColumnReference | None = None,
        *,
        dimensions: int,
        reserved_space: int = 1024,
        metric: USearchMetricKind = USearchMetricKind.COS,
        connectivity: int = 16,
        expansion_add: int = 128,
        expansion_search: int = 64,
        embedder: Any = None,
    ):
        metric_s = _metric_str(metric)
        super().__init__(
            data_column,
            metadata_column,
            dimensions,
            metric_s,
            embedder,
            make_index=lambda: _make_bf_index(dimensions, metric_s, reserved_space),
        )


class LshKnn(_KnnInnerIndex):
    """Approximate KNN via random-projection LSH (reference ``LshKnn:262``)."""

    def __init__(
        self,
        data_column: expr.ColumnReference,
        metadata_column: expr.ColumnReference | None = None,
        *,
        dimensions: int,
        n_or: int = 8,
        n_and: int = 4,
        bucket_length: float = 4.0,
        distance_type: str = "euclidean",
        embedder: Any = None,
    ):
        metric = "cos" if distance_type == "cosine" else "l2sq"
        super().__init__(
            data_column,
            metadata_column,
            dimensions,
            metric,
            embedder,
            make_index=lambda: LshKnnIndex(
                dimensions,
                metric=metric,
                bucket_length=bucket_length,
                n_or=n_or,
                n_and=n_and,
            ),
        )


@dataclass
class _KnnFactoryBase(AbstractRetrieverFactory):
    dimensions: int | None = None
    reserved_space: int = 1024
    metric: Any = None
    embedder: Any = None

    index_cls: Any = None

    def build_inner_index(
        self,
        data_column: expr.ColumnReference,
        metadata_column: expr.ColumnReference | None = None,
    ) -> InnerIndex:
        dims = self.dimensions
        if dims is None and self.embedder is not None:
            dims = _probe_embedder_dims(self.embedder)
        assert dims is not None, "dimensions required (or an embedder to probe)"
        kwargs: dict = dict(dimensions=dims, embedder=self.embedder)
        if self.metric is not None:
            kwargs["metric"] = self.metric
        if self.index_cls in (BruteForceKnn, USearchKnn):
            kwargs["reserved_space"] = self.reserved_space
        return self.index_cls(data_column, metadata_column, **kwargs)

    def build_index(
        self,
        data_column: expr.ColumnReference,
        data_table: Table,
        metadata_column: expr.ColumnReference | None = None,
        **kwargs: Any,
    ) -> DataIndex:
        return DataIndex(data_table, self.build_inner_index(data_column, metadata_column))


def _probe_embedder_dims(embedder: Any) -> int:
    if hasattr(embedder, "get_embedding_dimension"):
        return int(embedder.get_embedding_dimension())
    if hasattr(embedder, "__wrapped__"):
        sample = embedder.__wrapped__("test")
        return len(sample)
    func = getattr(embedder, "func", None)
    if func is not None:
        import asyncio

        result = func("test")
        if asyncio.iscoroutine(result):
            result = asyncio.run(result)
        return len(result)
    raise ValueError("cannot determine embedder dimensionality")


class BruteForceKnnFactory(_KnnFactoryBase):
    def __init__(
        self,
        *,
        dimensions: int | None = None,
        reserved_space: int = 1024,
        auxiliary_space: int = 1024,
        metric: BruteForceKnnMetricKind = BruteForceKnnMetricKind.L2SQ,
        embedder: Any = None,
    ):
        super().__init__(dimensions, reserved_space, metric, embedder, BruteForceKnn)


class UsearchKnnFactory(_KnnFactoryBase):
    def __init__(
        self,
        *,
        dimensions: int | None = None,
        reserved_space: int = 1024,
        metric: USearchMetricKind = USearchMetricKind.COS,
        connectivity: int = 16,
        expansion_add: int = 128,
        expansion_search: int = 64,
        embedder: Any = None,
    ):
        super().__init__(dimensions, reserved_space, metric, embedder, USearchKnn)


USearchKnnFactory = UsearchKnnFactory


class LshKnnFactory(_KnnFactoryBase):
    def __init__(
        self,
        *,
        dimensions: int | None = None,
        n_or: int = 8,
        n_and: int = 4,
        bucket_length: float = 4.0,
        distance_type: str = "euclidean",
        embedder: Any = None,
    ):
        super().__init__(dimensions, 1024, None, embedder, LshKnn)
        self.n_or = n_or
        self.n_and = n_and
        self.bucket_length = bucket_length
        self.distance_type = distance_type

    def build_inner_index(
        self,
        data_column: expr.ColumnReference,
        metadata_column: expr.ColumnReference | None = None,
    ) -> InnerIndex:
        dims = self.dimensions or _probe_embedder_dims(self.embedder)
        return LshKnn(
            data_column,
            metadata_column,
            dimensions=dims,
            n_or=self.n_or,
            n_and=self.n_and,
            bucket_length=self.bucket_length,
            distance_type=self.distance_type,
            embedder=self.embedder,
        )


# -- document-index presets (reference ``:407-528`` + vector_document_index.py) ----


def default_brute_force_knn_document_index(
    data_column: expr.ColumnReference,
    data_table: Table,
    *,
    dimensions: int,
    embedder: Any = None,
    metadata_column: expr.ColumnReference | None = None,
    metric: BruteForceKnnMetricKind = BruteForceKnnMetricKind.COS,
) -> DataIndex:
    return DataIndex(
        data_table,
        BruteForceKnn(
            data_column,
            metadata_column,
            dimensions=dimensions,
            metric=metric,
            embedder=embedder,
        ),
    )


def default_usearch_knn_document_index(
    data_column: expr.ColumnReference,
    data_table: Table,
    *,
    dimensions: int,
    embedder: Any = None,
    metadata_column: expr.ColumnReference | None = None,
    metric: USearchMetricKind = USearchMetricKind.COS,
) -> DataIndex:
    return DataIndex(
        data_table,
        USearchKnn(
            data_column,
            metadata_column,
            dimensions=dimensions,
            metric=metric,
            embedder=embedder,
        ),
    )


def default_lsh_knn_document_index(
    data_column: expr.ColumnReference,
    data_table: Table,
    *,
    dimensions: int,
    embedder: Any = None,
    metadata_column: expr.ColumnReference | None = None,
) -> DataIndex:
    return DataIndex(
        data_table,
        LshKnn(data_column, metadata_column, dimensions=dimensions, embedder=embedder),
    )


def _make_ivf_index(
    dimensions: int, metric_s: str, reserved_space: int, n_clusters: int, n_probe: int
) -> Any:
    """Engine-facing IVF index instance; a configured multi-shard mesh swaps in
    the row-sharded IVF store (per-shard fused probe→gather→score kernel +
    top-k merge — the same merge contract as the dense sharded store)."""
    from pathway_tpu.ops.knn import IvfKnnIndex
    from pathway_tpu.parallel.mesh import data_shards, get_default_mesh

    mesh = get_default_mesh()
    return IvfKnnIndex(
        dimensions,
        metric=metric_s,
        initial_capacity=max(16, reserved_space),
        n_clusters=n_clusters,
        n_probe=n_probe,
        mesh=mesh if data_shards(mesh) > 1 else None,
    )


class IvfKnn(_KnnInnerIndex):
    """Approximate KNN via IVF-Flat on the TPU — the reference's ANN slot
    (``USearchKnn`` over HNSW, ``usearch_integration.rs:20``) filled with a
    coarse-quantizer design that maps to the MXU (``ops/knn_ivf.py``).
    ``n_probe`` trades recall for candidate volume; ``n_probe == n_clusters``
    degenerates to exact search."""

    _device_queries = True

    def __init__(
        self,
        data_column: expr.ColumnReference,
        metadata_column: expr.ColumnReference | None = None,
        *,
        dimensions: int,
        reserved_space: int = 1024,
        n_clusters: int = 64,
        n_probe: int = 8,
        metric: BruteForceKnnMetricKind = BruteForceKnnMetricKind.L2SQ,
        embedder: Any = None,
    ):
        metric_s = _metric_str(metric)
        super().__init__(
            data_column,
            metadata_column,
            dimensions,
            metric_s,
            embedder,
            make_index=lambda: _make_ivf_index(
                dimensions, metric_s, reserved_space, n_clusters, n_probe
            ),
        )


class IvfKnnFactory(_KnnFactoryBase):
    def __init__(
        self,
        *,
        dimensions: int | None = None,
        reserved_space: int = 1024,
        n_clusters: int = 64,
        n_probe: int = 8,
        metric: BruteForceKnnMetricKind = BruteForceKnnMetricKind.L2SQ,
        embedder: Any = None,
    ):
        super().__init__(dimensions, reserved_space, metric, embedder, IvfKnn)
        self.n_clusters = n_clusters
        self.n_probe = n_probe

    def build_inner_index(
        self,
        data_column: expr.ColumnReference,
        metadata_column: expr.ColumnReference | None = None,
    ) -> InnerIndex:
        dims = self.dimensions
        if dims is None and self.embedder is not None:
            dims = _probe_embedder_dims(self.embedder)
        assert dims is not None, "dimensions required (or an embedder to probe)"
        return IvfKnn(
            data_column,
            metadata_column,
            dimensions=dims,
            reserved_space=self.reserved_space,
            n_clusters=self.n_clusters,
            n_probe=self.n_probe,
            metric=self.metric,
            embedder=self.embedder,
        )
