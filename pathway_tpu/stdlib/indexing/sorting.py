"""Sorted-index subsystem: sorted binary trees over table rows, prev/next
retrieval, and nearest-non-None lookups along a sort order.

Parity target: reference ``python/pathway/stdlib/indexing/sorting.py:92``
(``build_sorted_index`` / ``sort_from_index`` / ``retrieve_prev_next_values``).
The reference has no engine-level sort, so it grows a treap through rounds of
``pw.iterate`` ix/groupby steps; here the tree is built INSIDE the engine
(``SortedIndexEvaluator``: one O(n) cartesian-tree pass per touched instance,
incremental diffs per commit), and only the genuinely relational pieces —
tree-order traversal of a user-supplied tree, chained value lookup — run as
pointer-doubling ``pw.iterate`` graphs.
"""

from __future__ import annotations

from typing import Any, Dict

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table

__all__ = [
    "SortedIndex",
    "build_sorted_index",
    "sort_from_index",
    "retrieve_prev_next_values",
]


# the reference types this as a TypedDict {"index": Table, "oracle": Table}
SortedIndex = Dict[str, Table]


def build_sorted_index(nodes: Table, key: Any = None, instance: Any = None) -> SortedIndex:
    """Sorted binary tree (treap with key-hash priorities) over ``nodes``.

    Returns ``{"index": ..., "oracle": ...}``: ``index`` shares ``nodes``'
    universe and carries ``key``/``left``/``right``/``parent``/``instance``
    columns (tree pointers, in-order = key order); ``oracle`` holds one row per
    instance, keyed by instance, with the tree root in ``root``.

    Reference: ``stdlib/indexing/sorting.py:92`` ``build_sorted_index``.
    """
    key_e = nodes._resolve(key if key is not None else nodes.key)
    if instance is None and "instance" in nodes.column_names():
        instance = nodes.instance
    instance_e = nodes._resolve(instance) if instance is not None else None
    node = G.add_node(
        pg.SortedIndexNode(inputs=[nodes], key=key_e, instance=instance_e)
    )
    columns = {
        "key": sch.ColumnSchema("key", dt.ANY),
        "left": sch.ColumnSchema("left", dt.Optional_(dt.POINTER)),
        "right": sch.ColumnSchema("right", dt.Optional_(dt.POINTER)),
        "parent": sch.ColumnSchema("parent", dt.Optional_(dt.POINTER)),
        "instance": sch.ColumnSchema("instance", dt.ANY),
    }
    schema = sch.schema_from_columns(columns, "sorted_index")
    index = Table(node, schema, universe=nodes._universe, name="sorted_index")
    roots = index.filter(index.parent.is_none())
    oracle = roots.select(roots.instance, root=roots.id).with_id_from(roots.instance)
    return {"index": index, "oracle": oracle}


def sort_from_index(index: Table, oracle: Table | None = None) -> Table:
    """In-order prev/next pointers for a binary tree given as
    ``left``/``right``/``parent`` columns (any tree, not only ours).

    The successor of a node is the leftmost node of its right subtree, else the
    nearest ancestor holding it in a left subtree (symmetrically for the
    predecessor). Subtree-extreme and ancestor chains close by pointer doubling
    inside ``pw.iterate`` — O(log depth) rounds.

    Reference: ``stdlib/indexing/sorting.py:137`` ``sort_from_index``.
    """
    import pathway_tpu as pw

    def _up_if_child(parent_child: Any, me: Any, parent: Any) -> Any:
        # the ancestor chain hop: step to the parent while we are its
        # right (resp. left) child, else stay put (chain end)
        return parent if parent_child == me and parent is not None else me

    par = index.ix(index.parent, optional=True)
    state0 = index.select(
        left=index.left,
        right=index.right,
        parent=index.parent,
        lm=expr.coalesce(index.left, index.id),
        rm=expr.coalesce(index.right, index.id),
        up_r=expr.apply_with_type(_up_if_child, dt.POINTER, par.right, index.id, index.parent),
        up_l=expr.apply_with_type(_up_if_child, dt.POINTER, par.left, index.id, index.parent),
    )

    def close(t: Table) -> Table:
        return t.select(
            left=t.left,
            right=t.right,
            parent=t.parent,
            lm=t.ix(t.lm).lm,
            rm=t.ix(t.rm).rm,
            up_r=t.ix(t.up_r).up_r,
            up_l=t.ix(t.up_l).up_l,
        )

    closed = pw.iterate(lambda t: dict(t=close(t)), t=state0).t
    closed.promise_universe_is_equal_to(index)
    closed = closed.with_universe_of(index)
    return closed.select(
        prev=expr.coalesce(
            closed.ix(closed.left, optional=True).rm,
            closed.ix(closed.up_l).parent,
        ),
        next=expr.coalesce(
            closed.ix(closed.right, optional=True).lm,
            closed.ix(closed.up_r).parent,
        ),
    )


def retrieve_prev_next_values(ordered_table: Table, value: Any = None) -> Table:
    """For each row of a prev/next-chained table: pointers to the nearest rows
    (including the row itself) whose ``value`` is present, looking backwards
    (``prev_value``) and forwards (``next_value``).

    Missing means None — or NaN, since this engine materializes absent float
    cells as NaN. Chains over missing runs close by pointer doubling.

    Reference: ``stdlib/indexing/sorting.py:183`` ``retrieve_prev_next_values``.
    """
    import pathway_tpu as pw

    value_ref = ordered_table.value if value is None else ordered_table[
        value.name if hasattr(value, "name") else str(value)
    ]

    def _self_if_known(v: Any, me: Any) -> Any:
        return me if v is not None and v == v else None

    state0 = ordered_table.select(
        prev=ordered_table.prev,
        next=ordered_table.next,
        prev_value=expr.apply_with_type(
            _self_if_known, dt.Optional_(dt.POINTER), value_ref, ordered_table.id
        ),
        next_value=expr.apply_with_type(
            _self_if_known, dt.Optional_(dt.POINTER), value_ref, ordered_table.id
        ),
    )

    def step(t: Table) -> Table:
        back = t.ix(t.prev, optional=True)
        fwd = t.ix(t.next, optional=True)
        return t.select(
            # unresolved rows skip over unresolved neighbors (doubling)
            prev=expr.if_else(
                t.prev_value.is_none() & back.prev_value.is_none(), back.prev, t.prev
            ),
            next=expr.if_else(
                t.next_value.is_none() & fwd.next_value.is_none(), fwd.next, t.next
            ),
            prev_value=expr.coalesce(t.prev_value, back.prev_value),
            next_value=expr.coalesce(t.next_value, fwd.next_value),
        )

    closed = pw.iterate(lambda t: dict(t=step(t)), t=state0).t
    closed.promise_universe_is_equal_to(ordered_table)
    closed = closed.with_universe_of(ordered_table)
    return closed.select(closed.prev_value, closed.next_value)
