"""Index stdlib (parity: reference ``stdlib/indexing``)."""

from pathway_tpu.stdlib.indexing.bm25 import TantivyBM25, TantivyBM25Factory
from pathway_tpu.stdlib.indexing.data_index import DataIndex, InnerIndex
from pathway_tpu.stdlib.indexing.hybrid_index import HybridIndex, HybridIndexFactory
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnn,
    BruteForceKnnFactory,
    BruteForceKnnMetricKind,
    IvfKnn,
    IvfKnnFactory,
    LshKnn,
    LshKnnFactory,
    USearchKnn,
    USearchKnnFactory,
    USearchMetricKind,
    default_brute_force_knn_document_index,
    default_lsh_knn_document_index,
    default_usearch_knn_document_index,
)
from pathway_tpu.stdlib.indexing.retrievers import AbstractRetrieverFactory
from pathway_tpu.stdlib.indexing.sorting import (
    SortedIndex,
    build_sorted_index,
    retrieve_prev_next_values,
    sort_from_index,
)
from pathway_tpu.stdlib.indexing.vector_document_index import (
    default_vector_document_index,
)
from pathway_tpu.stdlib.indexing.full_text_document_index import (
    default_full_text_document_index,
)

__all__ = [
    "AbstractRetrieverFactory",
    "BruteForceKnn",
    "BruteForceKnnFactory",
    "BruteForceKnnMetricKind",
    "DataIndex",
    "HybridIndex",
    "HybridIndexFactory",
    "InnerIndex",
    "IvfKnn",
    "IvfKnnFactory",
    "LshKnn",
    "LshKnnFactory",
    "SortedIndex",
    "build_sorted_index",
    "retrieve_prev_next_values",
    "sort_from_index",
    "TantivyBM25",
    "TantivyBM25Factory",
    "USearchKnn",
    "USearchKnnFactory",
    "USearchMetricKind",
    "default_brute_force_knn_document_index",
    "default_full_text_document_index",
    "default_lsh_knn_document_index",
    "default_usearch_knn_document_index",
    "default_vector_document_index",
]
