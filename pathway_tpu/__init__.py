"""pathway_tpu — a TPU-native incremental dataflow framework.

A from-scratch re-design of the Pathway contract (declarative ``Table`` programs over update
streams, executed incrementally) on a JAX/XLA/Pallas substrate: columnar keyed state, batch
deltas per commit, jit'd kernels for dense work, device-mesh sharding for scale-out.

Import as ``import pathway_tpu as pw`` — the namespace mirrors the reference's ``pathway``
package (``python/pathway/__init__.py``).
"""

from __future__ import annotations

# core types
from pathway_tpu.internals import dtype as _dtype_mod
from pathway_tpu.internals.dtype import DType
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.keys import Pointer
from pathway_tpu.internals.schema import (
    ColumnDefinition,
    Schema,
    column_definition,
    schema_builder,
    schema_from_csv,
    schema_from_dict,
    schema_from_pandas,
    schema_from_types,
)
from pathway_tpu.internals.table import Joinable, Table, TableSlice
from pathway_tpu.internals.joins import JoinKind, JoinMode, JoinResult
from pathway_tpu.internals.groupbys import GroupedTable
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    apply,
    apply_async,
    apply_with_type,
    cast,
    coalesce,
    declare_type,
    fill_error,
    if_else,
    make_tuple,
    require,
    unwrap,
)
from pathway_tpu.internals.thisclass import left, right, this
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.custom_reducers import BaseCustomAccumulator
from pathway_tpu.internals.parse_graph import G as parse_graph_G
from pathway_tpu.engine.runner import run, run_all
from pathway_tpu.internals import udfs
from pathway_tpu.internals.udfs import (
    UDF,
    AsyncRetryStrategy,
    CacheStrategy,
    DiskCache,
    ExponentialBackoffRetryStrategy,
    FixedDelayRetryStrategy,
    FullyAsyncExecutor,
    InMemoryCache,
    NoRetryStrategy,
    async_executor,
    auto_executor,
    fully_async_executor,
    sync_executor,
    udf,
)
from pathway_tpu.internals.monitoring import MonitoringLevel
from pathway_tpu.internals.iterate import iterate, iteration_limit
from pathway_tpu.internals.row_transformer import (
    ClassArg,
    attribute,
    input_attribute,
    input_method,
    method,
    output_attribute,
    transformer,
)

from pathway_tpu.internals.interactive import LiveTable, enable_interactive_mode
from pathway_tpu.internals.errors import global_error_log, local_error_log

# namespaces
from pathway_tpu import debug, demo, io
from pathway_tpu import persistence
from pathway_tpu.stdlib import graphs, indexing, ml, ordered, statistical, stateful, temporal, viz, utils as _stdlib_utils
from pathway_tpu.stdlib.utils.async_transformer import AsyncTransformer
from pathway_tpu.stdlib.utils.pandas_transformer import pandas_transformer
from pathway_tpu.internals.sql import sql
from pathway_tpu.internals.yaml_loader import load_yaml

# engine alias (parity: ``pathway.engine``)
from pathway_tpu import engine

__version__ = "0.1.0"

Date = _dtype_mod.DATE_TIME_NAIVE
DateTimeNaive = _dtype_mod.DATE_TIME_NAIVE
DateTimeUtc = _dtype_mod.DATE_TIME_UTC
Duration = _dtype_mod.DURATION


def __getattr__(name: str):
    if name == "xpacks":
        import pathway_tpu.xpacks as xpacks

        return xpacks
    raise AttributeError(name)


__all__ = [
    "AsyncTransformer",
    "udfs",
    "BaseCustomAccumulator",
    "CacheStrategy",
    "ColumnDefinition",
    "ColumnExpression",
    "ColumnReference",
    "DType",
    "DiskCache",
    "GroupedTable",
    "InMemoryCache",
    "Joinable",
    "JoinKind",
    "JoinMode",
    "JoinResult",
    "Json",
    "MonitoringLevel",
    "Pointer",
    "Schema",
    "Table",
    "TableSlice",
    "UDF",
    "apply",
    "apply_async",
    "apply_with_type",
    "cast",
    "coalesce",
    "column_definition",
    "debug",
    "declare_type",
    "demo",
    "engine",
    "fill_error",
    "graphs",
    "if_else",
    "indexing",
    "io",
    "iterate",
    "left",
    "load_yaml",
    "make_tuple",
    "ml",
    "ordered",
    "pandas_transformer",
    "persistence",
    "reducers",
    "require",
    "right",
    "run",
    "run_all",
    "schema_builder",
    "schema_from_csv",
    "schema_from_dict",
    "schema_from_pandas",
    "schema_from_types",
    "sql",
    "statistical",
    "stateful",
    "temporal",
    "this",
    "udf",
    "unwrap",
]
